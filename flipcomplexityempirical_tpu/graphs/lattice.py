"""Graph substrate: host-side lattice construction -> static device arrays.

TPU-first re-design of the graph layer the reference consumes from networkx
(reference: grid_chain_sec11.py:186-260, Frankenstein_chain.py:186-234).
Instead of dict-of-dicts adjacency mutated per step, a graph here is a set of
frozen padded arrays uploaded to device once:

- ``edges``:    ``int32[E, 2]`` canonical (lexicographically sorted) edge list.
- ``nbr``:      ``int32[N, D]`` padded neighbor table. Padding slots hold the
                node's own index, so a gathered "neighbor assignment" equals
                the node's own assignment and contributes nothing to cut
                deltas by construction.
- ``nbr_edge``: ``int32[N, D]`` edge index per neighbor slot (pad 0; always
                used together with ``nbr_mask`` so pad slots scatter zeros).
- patch tables (``patch_nodes``, ``patch_adj``, sizes): a per-node radius-r
  ball (r=2 default; 3 for hex lattices, see builders.hex_lattice) encoded
  as <=32-node bitset adjacency, used by the O(P^2) local
  contiguity check (kernel/contiguity.py). The local check is *sufficient*
  (patch-connected => flip keeps the district connected) but not necessary:
  a district connected only around a long detour fails it. It is exact for
  simply-connected districts on these lattices; kernels expose
  ``contiguity='patch'|'exact'`` and the exact masked-BFS mode matches
  gerrychain's ``single_flip_contiguous`` semantics unconditionally.
- ``wall_id``:  ``int8[E]`` wall classification per edge for the reference's
                ``boundary_slope`` updater parity (grid_chain_sec11.py:55-78:
                walls 0..3 are x==0 / y==0 / x==max / y==max; 4 marks the four
                corner diagonal edges of the sec11 graph).
- ``frame_mask``: ``bool[N]`` the reference's per-node ``boundary_node``
                attribute (grid_chain_sec11.py:229-234).

Everything dynamic (assignment, cut masks, populations per district) lives in
``state.ChainState``; everything here is immutable for the lifetime of a run,
which is what lets XLA treat it as loop-invariant and keep the hot flip
kernel free of host traffic.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Sequence

import numpy as np

from flax import struct

import jax.numpy as jnp

# Patch bitsets are uint32 words: a patch ball larger than 32 nodes cannot
# be encoded and the graph falls back to the exact (BFS) contiguity checker.
MAX_PATCH = 32


@struct.dataclass
class DeviceGraph:
    """The static, device-resident view of a lattice graph (a JAX pytree).

    All kernels take this as an argument; XLA hoists it out of the step loop.
    Shapes: N nodes, E edges, D max degree, P max patch size.
    """

    edges: jnp.ndarray        # int32[E, 2]
    nbr: jnp.ndarray          # int32[N, D], pad = self
    nbr_mask: jnp.ndarray     # bool[N, D]
    nbr_edge: jnp.ndarray     # int32[N, D], pad = 0 (mask before scatter)
    deg: jnp.ndarray          # int32[N]
    pop: jnp.ndarray          # int32[N] node population weights
    coords: jnp.ndarray       # float32[N, 2] planar positions (plot/slope)
    frame_mask: jnp.ndarray   # bool[N]   reference "boundary_node" attr
    frame_idx: jnp.ndarray    # int32[F]  indices of frame nodes (static)
    wall_id: jnp.ndarray      # int8[E]   -1 none, 0..3 walls, 4 corner diag
    edge_len: jnp.ndarray     # f32[E]    boundary-length weight (1 = count)
    patch_nodes: jnp.ndarray  # int32[N, P], pad = self
    patch_adj: jnp.ndarray    # uint32[N, P] bitset adjacency within patch
    patch_size: jnp.ndarray   # int32[N]
    center: jnp.ndarray       # float32[2] angle-metric center

    @property
    def n_nodes(self) -> int:
        return self.nbr.shape[0]

    @property
    def n_edges(self) -> int:
        return self.edges.shape[0]

    @property
    def max_deg(self) -> int:
        return self.nbr.shape[1]

    @property
    def max_patch(self) -> int:
        return self.patch_nodes.shape[1]


@dataclasses.dataclass(frozen=True, eq=False)
class LatticeGraph:
    """Host-side graph: numpy arrays + label metadata + a DeviceGraph view.

    ``labels`` keeps the original (e.g. ``(x, y)``) node labels in index
    order so experiment drivers can translate between the reference's
    dict-keyed world and our dense arrays.
    """

    name: str
    labels: tuple                 # tuple of hashable node labels, index order
    edges: np.ndarray             # int32[E, 2]
    nbr: np.ndarray               # int32[N, D]
    nbr_mask: np.ndarray          # bool[N, D]
    nbr_edge: np.ndarray          # int32[N, D]
    deg: np.ndarray               # int32[N]
    pop: np.ndarray               # int32[N]
    coords: np.ndarray            # float64[N, 2]
    frame_mask: np.ndarray        # bool[N]
    wall_id: np.ndarray           # int8[E]
    edge_len: np.ndarray          # f32[E] boundary-length weights
    patch_nodes: np.ndarray       # int32[N, P]
    patch_adj: np.ndarray         # uint32[N, P]
    patch_size: np.ndarray        # int32[N]
    patch_ok: bool                # False => local check unavailable
    center: tuple = (20.0, 20.0)  # angle-metric center, ref *:391-394

    @property
    def n_nodes(self) -> int:
        return int(self.nbr.shape[0])

    @property
    def n_edges(self) -> int:
        return int(self.edges.shape[0])

    @property
    def max_deg(self) -> int:
        return int(self.nbr.shape[1])

    @property
    def index(self) -> dict:
        """label -> node index map (built lazily, cached on the instance)."""
        idx = self.__dict__.get("_index")
        if idx is None:
            idx = {lab: i for i, lab in enumerate(self.labels)}
            object.__setattr__(self, "_index", idx)
        return idx

    def device(self) -> DeviceGraph:
        dg = self.__dict__.get("_device")
        if dg is None:
            dg = DeviceGraph(
                edges=jnp.asarray(self.edges, jnp.int32),
                nbr=jnp.asarray(self.nbr, jnp.int32),
                nbr_mask=jnp.asarray(self.nbr_mask),
                nbr_edge=jnp.asarray(self.nbr_edge, jnp.int32),
                deg=jnp.asarray(self.deg, jnp.int32),
                pop=jnp.asarray(self.pop, jnp.int32),
                coords=jnp.asarray(self.coords, jnp.float32),
                frame_mask=jnp.asarray(self.frame_mask),
                frame_idx=jnp.asarray(
                    np.nonzero(self.frame_mask)[0], jnp.int32),
                wall_id=jnp.asarray(self.wall_id, jnp.int8),
                edge_len=jnp.asarray(self.edge_len, jnp.float32),
                patch_nodes=jnp.asarray(self.patch_nodes, jnp.int32),
                patch_adj=jnp.asarray(self.patch_adj, jnp.uint32),
                patch_size=jnp.asarray(self.patch_size, jnp.int32),
                center=jnp.asarray(self.center, jnp.float32),
            )
            object.__setattr__(self, "_device", dg)
        return dg

    # -- conveniences used by experiments / tests ---------------------------

    def assignment_from_dict(self, d: dict, dtype=np.int8) -> np.ndarray:
        """Map a reference-style {label: district} dict to a dense array.

        Every node must be covered; a partial dict raises instead of leaving
        uninitialized entries.
        """
        sentinel = np.iinfo(dtype).min
        out = np.full(self.n_nodes, sentinel, dtype=dtype)
        for lab, v in d.items():
            out[self.index[lab]] = v
        if (out == sentinel).any():
            missing = [self.labels[i] for i in
                       np.nonzero(out == sentinel)[0][:5]]
            raise ValueError(
                f"assignment dict missing {int((out == sentinel).sum())} "
                f"nodes, e.g. {missing}")
        return out

    def assignment_to_dict(self, arr: np.ndarray) -> dict:
        return {lab: arr[i].item() for i, lab in enumerate(self.labels)}


def build_lattice(
    adjacency: dict,
    *,
    name: str = "graph",
    coords: Optional[dict] = None,
    pop: Optional[dict] = None,
    frame: Optional[Callable[[Any], bool]] = None,
    wall: Optional[Callable[[Any, Any], int]] = None,
    center: tuple = (20.0, 20.0),
    node_order: Optional[Sequence] = None,
    patch_radius: int = 2,
) -> LatticeGraph:
    """Build a LatticeGraph from a plain adjacency dict {label: iterable}.

    ``adjacency`` may come from networkx (``{n: set(G[n])}``) or be hand
    rolled; this function owns canonicalization (sorted node order, sorted
    edge list) so that edge indices — and therefore the deterministic
    "first two wall edges" selection of the slope metric (see
    kernel/metrics.py; reference grid_chain_sec11.py:371-374 relies on
    arbitrary Python set order) — are reproducible across runs.
    """
    labels = list(node_order) if node_order is not None else sorted(adjacency)
    n = len(labels)
    index = {lab: i for i, lab in enumerate(labels)}

    edge_set = set()
    for u, nbrs in adjacency.items():
        iu = index[u]
        for v in nbrs:
            iv = index[v]
            if iu == iv:
                continue
            edge_set.add((min(iu, iv), max(iu, iv)))
    edges = np.array(sorted(edge_set), dtype=np.int32).reshape(-1, 2)
    e = edges.shape[0]

    # adjacency lists in index space
    adj_idx: list[list[tuple[int, int]]] = [[] for _ in range(n)]
    for ei in range(e):
        a, b = int(edges[ei, 0]), int(edges[ei, 1])
        adj_idx[a].append((b, ei))
        adj_idx[b].append((a, ei))
    deg = np.array([len(a) for a in adj_idx], dtype=np.int32)
    d = int(deg.max()) if n else 0

    nbr = np.tile(np.arange(n, dtype=np.int32)[:, None], (1, d))
    nbr_mask = np.zeros((n, d), dtype=bool)
    nbr_edge = np.zeros((n, d), dtype=np.int32)
    for i in range(n):
        for s, (j, ei) in enumerate(adj_idx[i]):
            nbr[i, s] = j
            nbr_mask[i, s] = True
            nbr_edge[i, s] = ei

    # --- radius-r patch bitsets for the local contiguity check ------------
    # patch order: neighbors first (same order as nbr slots) so the "seed"
    # bits of the check are simply bits [0, deg). The radius must cover half
    # of the largest face so that same-district neighbors of a flipped node
    # can reconnect around a face inside the patch: 2 for square/triangular
    # faces, 3 for hexagonal faces.
    patch_lists: list[list[int]] = []
    for i in range(n):
        first = [j for (j, _) in adj_idx[i]]
        seen = {i, *first}
        ordered = list(first)
        frontier = first
        for _ in range(patch_radius - 1):
            nxt = []
            for j in frontier:
                for (k2, _) in adj_idx[j]:
                    if k2 not in seen:
                        seen.add(k2)
                        nxt.append(k2)
            ordered.extend(nxt)
            frontier = nxt
        patch_lists.append(ordered)
    p = max((len(pl) for pl in patch_lists), default=0)
    patch_ok = p <= MAX_PATCH
    if not patch_ok:
        p = 1  # keep arrays tiny; kernel must use the exact checker
    patch_nodes = np.tile(np.arange(n, dtype=np.int32)[:, None], (1, p))
    patch_adj = np.zeros((n, p), dtype=np.uint32)
    patch_size = np.zeros(n, dtype=np.int32)
    if patch_ok:
        nbrsets = [set(j for (j, _) in a) for a in adj_idx]
        for i in range(n):
            pl = patch_lists[i]
            patch_size[i] = len(pl)
            pos = {j: s for s, j in enumerate(pl)}
            for s, j in enumerate(pl):
                patch_nodes[i, s] = j
                word = 0
                for k2 in nbrsets[j]:
                    t = pos.get(k2)
                    if t is not None:
                        word |= 1 << t
                patch_adj[i, s] = word

    coords_arr = np.zeros((n, 2), dtype=np.float64)
    if coords is not None:
        for lab, xy in coords.items():
            coords_arr[index[lab]] = xy
    else:
        for lab in labels:
            if isinstance(lab, tuple) and len(lab) == 2:
                coords_arr[index[lab]] = lab

    pop_arr = np.ones(n, dtype=np.int32)
    if pop is not None:
        for lab, v in pop.items():
            pop_arr[index[lab]] = v

    frame_mask = np.zeros(n, dtype=bool)
    if frame is not None:
        for lab in labels:
            frame_mask[index[lab]] = bool(frame(lab))

    wall_arr = np.full(e, -1, dtype=np.int8)
    if wall is not None:
        for ei in range(e):
            a, b = labels[edges[ei, 0]], labels[edges[ei, 1]]
            wall_arr[ei] = wall(a, b)

    return LatticeGraph(
        name=name,
        labels=tuple(labels),
        edges=edges,
        nbr=nbr,
        nbr_mask=nbr_mask,
        nbr_edge=nbr_edge,
        deg=deg,
        pop=pop_arr,
        coords=coords_arr,
        frame_mask=frame_mask,
        wall_id=wall_arr,
        edge_len=np.ones(e, dtype=np.float32),
        patch_nodes=patch_nodes,
        patch_adj=patch_adj,
        patch_size=patch_size,
        patch_ok=patch_ok,
        center=center,
    )


def from_networkx(g, **kwargs) -> LatticeGraph:
    """Build from a networkx graph (host-side convenience)."""
    adjacency = {n: list(g[n]) for n in g.nodes()}
    return build_lattice(adjacency, **kwargs)
