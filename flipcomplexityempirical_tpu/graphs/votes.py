"""Random vote attributes: the reference's pink/purple node columns.

grid_chain_sec11.py:223-228 seeds every node with Bernoulli(1/2) party
membership (``pink``/``purple``, exactly one of the two set to 1) for the
commented-out ``Election("Pink-Purple", ...)`` updater (line 307). Here the
columns are a dense (N, 2) array aligned with LatticeGraph node order, the
shape ``stats.partisan`` consumes directly.
"""

from __future__ import annotations

import numpy as np

from .lattice import LatticeGraph

PARTIES = ("pink", "purple")


def seed_votes(graph: LatticeGraph, seed: int, p: float = 0.5) -> np.ndarray:
    """(N, 2) int8: column 0 = pink, column 1 = purple; one vote per node
    (the reference's one-person-one-party attribute pair)."""
    rng = np.random.default_rng(seed)
    pink = (rng.random(graph.n_nodes) < p).astype(np.int8)
    return np.stack([pink, 1 - pink], axis=1)
