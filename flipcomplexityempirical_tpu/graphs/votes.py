"""Random vote attributes: the reference's pink/purple node columns.

grid_chain_sec11.py:223-228 seeds every node with Bernoulli(1/2) party
membership (``pink``/``purple``, exactly one of the two set to 1) for the
commented-out ``Election("Pink-Purple", ...)`` updater (line 307). Here the
columns are a dense (N, 2) array aligned with LatticeGraph node order, the
shape ``stats.partisan`` consumes directly.
"""

from __future__ import annotations

import numpy as np

from .lattice import LatticeGraph

PARTIES = ("pink", "purple")


class VoteAlignmentError(ValueError):
    """Typed mismatch between a vote array and the graph it scores.

    Raised BEFORE any tally: vote columns ingested from external data
    (shapefile/GeoJSON properties) that don't follow LatticeGraph node
    order would silently mis-attribute votes to districts — the failure
    mode must be loud and typed so the driver/service can classify it
    deterministic (no retry)."""


def validate_votes(graph: LatticeGraph, votes) -> np.ndarray:
    """Validate ``votes`` against ``graph``: 2-D (N, P) with one row per
    graph node in LatticeGraph node order, P >= 2 party columns, finite
    non-negative counts. Returns the array as numpy; raises
    VoteAlignmentError on any mismatch."""
    v = np.asarray(votes)
    name = getattr(graph, "name", None) or "graph"
    if v.ndim != 2:
        raise VoteAlignmentError(
            f"votes for {name!r} must be 2-D (nodes, parties); "
            f"got shape {v.shape}")
    if v.shape[0] != graph.n_nodes:
        raise VoteAlignmentError(
            f"votes rows ({v.shape[0]}) != nodes ({graph.n_nodes}) of "
            f"{name!r}: vote columns must align with LatticeGraph node "
            f"order")
    if v.shape[1] < 2:
        raise VoteAlignmentError(
            f"votes for {name!r} needs >= 2 party columns; "
            f"got {v.shape[1]}")
    vf = v.astype(np.float64)
    if not np.isfinite(vf).all() or (vf < 0).any():
        raise VoteAlignmentError(
            f"votes for {name!r} must be finite and non-negative")
    return v


def seed_votes(graph: LatticeGraph, seed: int, p: float = 0.5) -> np.ndarray:
    """(N, 2) int8: column 0 = pink, column 1 = purple; one vote per node
    (the reference's one-person-one-party attribute pair)."""
    rng = np.random.default_rng(seed)
    pink = (rng.random(graph.n_nodes) < p).astype(np.int8)
    return np.stack([pink, 1 - pink], axis=1)
