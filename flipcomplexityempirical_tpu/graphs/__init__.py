from .lattice import LatticeGraph, DeviceGraph, build_lattice, from_networkx
from .builders import (
    square_grid, grid_sec11, triangular_lattice, hex_lattice, frankengraph,
    sec11_plan, frank_plan, stripes_plan, PARITY_LABELS,
)
from .shapefile import read_shapefile, write_shapefile
from .dualgraph import (
    GeoAttributes, from_geojson, from_shapefile, synthetic_precincts,
    voronoi_precincts,
)
from .votes import seed_votes, validate_votes, VoteAlignmentError, PARTIES

__all__ = [
    "LatticeGraph", "DeviceGraph", "build_lattice", "from_networkx",
    "square_grid", "grid_sec11", "triangular_lattice", "hex_lattice",
    "frankengraph", "sec11_plan", "frank_plan", "stripes_plan",
    "PARITY_LABELS",
    "GeoAttributes", "from_geojson", "from_shapefile",
    "synthetic_precincts", "voronoi_precincts",
    "read_shapefile", "write_shapefile",
    "seed_votes", "validate_votes", "VoteAlignmentError", "PARTIES",
]
