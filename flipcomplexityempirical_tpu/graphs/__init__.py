from .lattice import LatticeGraph, DeviceGraph, build_lattice, from_networkx
from .builders import (
    square_grid, grid_sec11, triangular_lattice, hex_lattice, frankengraph,
    sec11_plan, frank_plan, stripes_plan, PARITY_LABELS,
)
from .dualgraph import (
    GeoAttributes, from_geojson, from_shapefile, synthetic_precincts,
)
from .votes import seed_votes, PARTIES

__all__ = [
    "LatticeGraph", "DeviceGraph", "build_lattice", "from_networkx",
    "square_grid", "grid_sec11", "triangular_lattice", "hex_lattice",
    "frankengraph", "sec11_plan", "frank_plan", "stripes_plan",
    "PARITY_LABELS",
    "GeoAttributes", "from_geojson", "from_shapefile",
    "synthetic_precincts",
    "seed_votes", "PARTIES",
]
