"""gerrychain-surface Partition: lazy memoized updaters over array substrate.

Re-implements (from call-site evidence only, SURVEY.md section 2.3; the
reference consumes it at grid_chain_sec11.py:316,366-400) the partition
protocol the reference scripts drive:

- ``Partition(graph, assignment, updaters)`` — graph may be a LatticeGraph
  or a networkx graph (converted on entry).
- ``part["key"]`` — lazy, memoized updater evaluation.
- ``part.flip(delta)`` — child partition sharing the graph; updaters with
  incremental paths (cut_edges, Tally) use parent + flips.
- ``part.parent`` / ``part.flips`` / ``part.assignment`` / ``part.parts`` /
  ``len(part)``.

This is the oracle backend: plain Python + numpy, no JAX. The vectorized
kernel (kernel/step.py) must match its semantics distributionally; tests
compare the two.
"""

from __future__ import annotations

from typing import Callable, Dict, Mapping, Optional

import numpy as np

from ..graphs.lattice import LatticeGraph, from_networkx


class _AssignmentView(Mapping):
    """Dict-like view of the dense assignment array, keyed by node label."""

    def __init__(self, graph: LatticeGraph, arr: np.ndarray):
        self._graph = graph
        self._arr = arr

    def __getitem__(self, label):
        return int(self._arr[self._graph.index[label]])

    def __iter__(self):
        return iter(self._graph.labels)

    def __len__(self):
        return len(self._graph.labels)

    def to_dict(self):
        return {lab: int(self._arr[i])
                for i, lab in enumerate(self._graph.labels)}


class Partition:
    def __init__(self, graph, assignment, updaters: Optional[Dict[str, Callable]] = None,
                 parent: Optional["Partition"] = None, flips: Optional[dict] = None):
        if parent is None:
            if not isinstance(graph, LatticeGraph):
                graph = from_networkx(graph)
            self.graph = graph
            if isinstance(assignment, dict):
                arr = graph.assignment_from_dict(assignment, dtype=np.int32)
            else:
                arr = np.asarray(assignment, dtype=np.int32).copy()
            self.assignment_array = arr
            self.updaters = dict(updaters or {})
        else:
            self.graph = parent.graph
            self.updaters = parent.updaters
            arr = parent.assignment_array.copy()
            for lab, v in flips.items():
                arr[self.graph.index[lab]] = int(v)
            self.assignment_array = arr
        self.parent = parent
        self.flips = flips  # None for an initial partition
        self.assignment = _AssignmentView(self.graph, self.assignment_array)
        self._cache: dict = {}

    # -- protocol -----------------------------------------------------------

    def flip(self, flips: dict) -> "Partition":
        return Partition(None, None, parent=self, flips=dict(flips))

    def __getitem__(self, key: str):
        if key not in self._cache:
            self._cache[key] = self.updaters[key](self)
        return self._cache[key]

    @property
    def parts(self) -> dict:
        if "_parts" not in self._cache:
            out: dict = {}
            for i, lab in enumerate(self.graph.labels):
                out.setdefault(int(self.assignment_array[i]), set()).add(lab)
            self._cache["_parts"] = out
        return self._cache["_parts"]

    def __len__(self):
        return len(self.parts)

    # -- array-level helpers used by updaters/constraints -------------------

    def cut_edge_mask(self) -> np.ndarray:
        """bool[E]: incremental when a parent mask exists (single flips touch
        only edges incident to flipped nodes)."""
        if "_cut_mask" in self._cache:
            return self._cache["_cut_mask"]
        g, a = self.graph, self.assignment_array
        if self.parent is not None and self.flips:
            mask = self.parent.cut_edge_mask().copy()
            for lab in self.flips:
                i = g.index[lab]
                d = int(g.deg[i])
                eids = g.nbr_edge[i, :d]
                mask[eids] = a[g.edges[eids, 0]] != a[g.edges[eids, 1]]
        else:
            mask = a[g.edges[:, 0]] != a[g.edges[:, 1]]
        self._cache["_cut_mask"] = mask
        return mask


# ---------------------------------------------------------------------------
# Updaters (gerrychain.updaters surface consumed at grid_chain_sec11.py:26,
# 299-308, plus the script-defined updaters of lines 147-156)
# ---------------------------------------------------------------------------

def cut_edges(partition: Partition):
    """Set of cut edges as (label_a, label_b) tuples in canonical edge-array
    order (gerrychain returns arbitrary-ordered tuples; consumers treat them
    as opaque pairs)."""
    g = partition.graph
    mask = partition.cut_edge_mask()
    return {(g.labels[g.edges[e, 0]], g.labels[g.edges[e, 1]])
            for e in np.nonzero(mask)[0]}


class Tally:
    """gerrychain.updaters.Tally('population'): district -> sum of node attr.

    Node attributes live on the LatticeGraph ``pop`` array when col ==
    'population'; other columns can be registered via ``columns``.
    """

    def __init__(self, col: str, alias: Optional[str] = None,
                 columns: Optional[Dict[str, np.ndarray]] = None):
        self.col = col
        self.alias = alias or col
        self.columns = columns or {}

    def _values(self, g: LatticeGraph) -> np.ndarray:
        if self.col in self.columns:
            return np.asarray(self.columns[self.col])
        if self.col == "population":
            return g.pop
        raise KeyError(f"Tally column {self.col!r} not registered")

    def __call__(self, partition: Partition) -> dict:
        vals = self._values(partition.graph)
        key = "_tally_" + self.alias
        if partition.parent is not None and partition.flips and \
                key in partition.parent._cache:
            out = dict(partition.parent._cache[key])
            for lab in partition.flips:
                i = partition.graph.index[lab]
                old = int(partition.parent.assignment_array[i])
                new = int(partition.assignment_array[i])
                if old != new:
                    out[old] = out.get(old, 0) - int(vals[i])
                    out[new] = out.get(new, 0) + int(vals[i])
        else:
            out = {}
            for i in range(partition.graph.n_nodes):
                d = int(partition.assignment_array[i])
                out[d] = out.get(d, 0) + int(vals[i])
        partition._cache[key] = out
        return out


class ElectionResults:
    """Per-district vote totals for one election, with the gerrychain
    score surface the reference imports (``mean_median``,
    ``efficiency_gap`` at grid_chain_sec11.py:26-30) as methods. The
    numeric conventions delegate to ``stats.partisan`` so the oracle and
    the batched path share one definition."""

    def __init__(self, name: str, parties: tuple, tallies: np.ndarray,
                 districts: tuple = ()):
        self.election = name
        self.parties = tuple(parties)
        self.tallies = np.asarray(tallies, dtype=np.int64)  # (K, P)
        self.districts = tuple(districts)  # district label per tally row

    def counts(self, party) -> tuple:
        return tuple(self.tallies[:, self.parties.index(party)])

    def percents(self, party) -> tuple:
        from ..stats import partisan
        return tuple(partisan._shares(self._party0_first(party)[None])[0])

    def wins(self, party) -> int:
        from ..stats import partisan
        t = self._party0_first(party)
        return int(partisan.seats_won(t[None])[0])

    def mean_median(self) -> float:
        from ..stats import partisan
        return float(partisan.mean_median(self.tallies[None])[0])

    def efficiency_gap(self) -> float:
        from ..stats import partisan
        return float(partisan.efficiency_gap(self.tallies[None])[0])

    def _party0_first(self, party) -> np.ndarray:
        j = self.parties.index(party)
        order = [j] + [i for i in range(len(self.parties)) if i != j]
        return self.tallies[:, order]


class Election:
    """gerrychain.updaters.Election('Pink-Purple', {'Pink': 'pink',
    'Purple': 'purple'}) — the updater the reference wires (commented) at
    grid_chain_sec11.py:307, over the Bernoulli(1/2) vote attributes of
    lines 223-228. ``columns`` maps attribute name -> (N,) vote array
    (graphs.votes.seed_votes provides the reference pair); tallies update
    incrementally on single flips like Tally."""

    def __init__(self, name: str, parties_to_columns: Dict[str, str],
                 columns: Dict[str, np.ndarray]):
        self.name = name
        self.parties = tuple(parties_to_columns)
        self.cols = [np.asarray(columns[attr], dtype=np.int64)
                     for attr in parties_to_columns.values()]

    def __call__(self, partition: Partition) -> ElectionResults:
        """Tally rows are indexed by SORTED district label, so the signed
        +1/-1 labels the reference loop uses (and 0..k-1 indices alike)
        tally correctly — a raw label-as-row-index scheme would alias -1
        onto the last row. All downstream scores are district-order
        invariant."""
        key = "_election_" + self.name
        if partition.parent is not None and partition.flips and \
                key in partition.parent._cache:
            districts, ptallies = partition.parent._cache[key]
            tallies = ptallies.copy()
            row = {d: r for r, d in enumerate(districts)}
            for lab in partition.flips:
                i = partition.graph.index[lab]
                old = int(partition.parent.assignment_array[i])
                new = int(partition.assignment_array[i])
                if old != new:
                    for j, col in enumerate(self.cols):
                        tallies[row[old], j] -= col[i]
                        tallies[row[new], j] += col[i]
        else:
            a = partition.assignment_array
            districts, inv = np.unique(a, return_inverse=True)
            districts = tuple(int(d) for d in districts)
            tallies = np.zeros((len(districts), len(self.cols)), np.int64)
            for j, col in enumerate(self.cols):
                np.add.at(tallies[:, j], inv, col)
        partition._cache[key] = (districts, tallies)
        return ElectionResults(self.name, self.parties, tallies,
                               districts=districts)


def mean_median(election_results: ElectionResults) -> float:
    """gerrychain.scores surface (imported by the reference at
    grid_chain_sec11.py:29)."""
    return election_results.mean_median()


def efficiency_gap(election_results: ElectionResults) -> float:
    """gerrychain.scores surface (grid_chain_sec11.py:30)."""
    return election_results.efficiency_gap()


def b_nodes_bi(partition: Partition):
    """Boundary-node set: all endpoints of cut edges
    (grid_chain_sec11.py:155-156)."""
    out = set()
    for (u, v) in partition["cut_edges"]:
        out.add(u)
        out.add(v)
    return out


def b_nodes_pairs(partition: Partition):
    """k-district boundary move set: {(node, other-side district)} pairs
    (grid_chain_sec11.py:151-153)."""
    out = set()
    for (u, v) in partition["cut_edges"]:
        out.add((u, partition.assignment[v]))
        out.add((v, partition.assignment[u]))
    return out


def make_geom_wait(rng: np.random.Generator):
    """The reference's geometric waiting-time updater
    (grid_chain_sec11.py:147-148): Geometric(p) - 1 with
    p = |b_nodes| / (n_nodes ** n_parts - 1). Memoized per partition by the
    updater protocol — a rejected (self-loop) step re-reads the same sample,
    which is load-bearing for wait-sum statistics parity."""

    def geom(partition: Partition):
        nb = len(partition["b_nodes"])
        denom = partition.graph.n_nodes ** len(partition.parts) - 1
        p = nb / denom
        return int(rng.geometric(p)) - 1

    return geom


def make_boundary_slope(wall_of_edge):
    """Wall-cut-edge collector (grid_chain_sec11.py:55-78): returns the cut
    edges lying along the outer walls (and, for sec11, the four corner
    diagonals). ``wall_of_edge(u_label, v_label) -> int`` classifies; -1 is
    'not on a wall'. Returned deterministically ordered by canonical edge
    index (the reference returns ``list(set(...))`` — arbitrary order — and
    then consumes elements [0] and [1]; see kernel/metrics.py for how the
    vectorized path mirrors this deterministic choice)."""

    def slope(partition: Partition):
        g = partition.graph
        mask = partition.cut_edge_mask()
        out = []
        for e in np.nonzero(mask)[0]:
            u, v = g.labels[g.edges[e, 0]], g.labels[g.edges[e, 1]]
            if wall_of_edge(u, v) >= 0:
                out.append((u, v))
        return out

    return slope


def bnodes_p(partition: Partition) -> list:
    """The reference's 'boundary' updater (grid_chain_sec11.py:294-297):
    the frame-flagged node labels, recomputed every step there (an O(n)
    scan of a constant — here read off the graph's frame mask)."""
    g = partition.graph
    return [g.labels[i] for i in np.nonzero(g.frame_mask)[0]]


def step_num(partition: Partition) -> int:
    parent = partition.parent
    if not parent:
        return 0
    return parent["step_num"] + 1
