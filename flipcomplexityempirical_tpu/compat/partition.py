"""gerrychain-surface Partition: lazy memoized updaters over array substrate.

Re-implements (from call-site evidence only, SURVEY.md section 2.3; the
reference consumes it at grid_chain_sec11.py:316,366-400) the partition
protocol the reference scripts drive:

- ``Partition(graph, assignment, updaters)`` — graph may be a LatticeGraph
  or a networkx graph (converted on entry).
- ``part["key"]`` — lazy, memoized updater evaluation.
- ``part.flip(delta)`` — child partition sharing the graph; updaters with
  incremental paths (cut_edges, Tally) use parent + flips.
- ``part.parent`` / ``part.flips`` / ``part.assignment`` / ``part.parts`` /
  ``len(part)``.

This is the oracle backend: plain Python + numpy, no JAX. The vectorized
kernel (kernel/step.py) must match its semantics distributionally; tests
compare the two.
"""

from __future__ import annotations

from typing import Callable, Dict, Mapping, Optional

import numpy as np

from ..graphs.lattice import LatticeGraph, from_networkx


class _AssignmentView(Mapping):
    """Dict-like view of the dense assignment array, keyed by node label."""

    def __init__(self, graph: LatticeGraph, arr: np.ndarray):
        self._graph = graph
        self._arr = arr

    def __getitem__(self, label):
        return int(self._arr[self._graph.index[label]])

    def __iter__(self):
        return iter(self._graph.labels)

    def __len__(self):
        return len(self._graph.labels)

    def to_dict(self):
        return {lab: int(self._arr[i])
                for i, lab in enumerate(self._graph.labels)}


class Partition:
    def __init__(self, graph, assignment, updaters: Optional[Dict[str, Callable]] = None,
                 parent: Optional["Partition"] = None, flips: Optional[dict] = None):
        if parent is None:
            if not isinstance(graph, LatticeGraph):
                graph = from_networkx(graph)
            self.graph = graph
            if isinstance(assignment, dict):
                arr = graph.assignment_from_dict(assignment, dtype=np.int32)
            else:
                arr = np.asarray(assignment, dtype=np.int32).copy()
            self.assignment_array = arr
            self.updaters = dict(updaters or {})
        else:
            self.graph = parent.graph
            self.updaters = parent.updaters
            arr = parent.assignment_array.copy()
            for lab, v in flips.items():
                arr[self.graph.index[lab]] = int(v)
            self.assignment_array = arr
        self.parent = parent
        self.flips = flips  # None for an initial partition
        self.assignment = _AssignmentView(self.graph, self.assignment_array)
        self._cache: dict = {}

    # -- protocol -----------------------------------------------------------

    def flip(self, flips: dict) -> "Partition":
        return Partition(None, None, parent=self, flips=dict(flips))

    def __getitem__(self, key: str):
        if key not in self._cache:
            self._cache[key] = self.updaters[key](self)
        return self._cache[key]

    @property
    def parts(self) -> dict:
        if "_parts" not in self._cache:
            out: dict = {}
            for i, lab in enumerate(self.graph.labels):
                out.setdefault(int(self.assignment_array[i]), set()).add(lab)
            self._cache["_parts"] = out
        return self._cache["_parts"]

    def __len__(self):
        return len(self.parts)

    # -- array-level helpers used by updaters/constraints -------------------

    def cut_edge_mask(self) -> np.ndarray:
        """bool[E]: incremental when a parent mask exists (single flips touch
        only edges incident to flipped nodes)."""
        if "_cut_mask" in self._cache:
            return self._cache["_cut_mask"]
        g, a = self.graph, self.assignment_array
        if self.parent is not None and self.flips:
            mask = self.parent.cut_edge_mask().copy()
            for lab in self.flips:
                i = g.index[lab]
                d = int(g.deg[i])
                eids = g.nbr_edge[i, :d]
                mask[eids] = a[g.edges[eids, 0]] != a[g.edges[eids, 1]]
        else:
            mask = a[g.edges[:, 0]] != a[g.edges[:, 1]]
        self._cache["_cut_mask"] = mask
        return mask


# ---------------------------------------------------------------------------
# Updaters (gerrychain.updaters surface consumed at grid_chain_sec11.py:26,
# 299-308, plus the script-defined updaters of lines 147-156)
# ---------------------------------------------------------------------------

def cut_edges(partition: Partition):
    """Set of cut edges as (label_a, label_b) tuples in canonical edge-array
    order (gerrychain returns arbitrary-ordered tuples; consumers treat them
    as opaque pairs)."""
    g = partition.graph
    mask = partition.cut_edge_mask()
    return {(g.labels[g.edges[e, 0]], g.labels[g.edges[e, 1]])
            for e in np.nonzero(mask)[0]}


class Tally:
    """gerrychain.updaters.Tally('population'): district -> sum of node attr.

    Node attributes live on the LatticeGraph ``pop`` array when col ==
    'population'; other columns can be registered via ``columns``.
    """

    def __init__(self, col: str, alias: Optional[str] = None,
                 columns: Optional[Dict[str, np.ndarray]] = None):
        self.col = col
        self.alias = alias or col
        self.columns = columns or {}

    def _values(self, g: LatticeGraph) -> np.ndarray:
        if self.col in self.columns:
            return np.asarray(self.columns[self.col])
        if self.col == "population":
            return g.pop
        raise KeyError(f"Tally column {self.col!r} not registered")

    def __call__(self, partition: Partition) -> dict:
        vals = self._values(partition.graph)
        key = "_tally_" + self.alias
        if partition.parent is not None and partition.flips and \
                key in partition.parent._cache:
            out = dict(partition.parent._cache[key])
            for lab in partition.flips:
                i = partition.graph.index[lab]
                old = int(partition.parent.assignment_array[i])
                new = int(partition.assignment_array[i])
                if old != new:
                    out[old] = out.get(old, 0) - int(vals[i])
                    out[new] = out.get(new, 0) + int(vals[i])
        else:
            out = {}
            for i in range(partition.graph.n_nodes):
                d = int(partition.assignment_array[i])
                out[d] = out.get(d, 0) + int(vals[i])
        partition._cache[key] = out
        return out


def b_nodes_bi(partition: Partition):
    """Boundary-node set: all endpoints of cut edges
    (grid_chain_sec11.py:155-156)."""
    out = set()
    for (u, v) in partition["cut_edges"]:
        out.add(u)
        out.add(v)
    return out


def b_nodes_pairs(partition: Partition):
    """k-district boundary move set: {(node, other-side district)} pairs
    (grid_chain_sec11.py:151-153)."""
    out = set()
    for (u, v) in partition["cut_edges"]:
        out.add((u, partition.assignment[v]))
        out.add((v, partition.assignment[u]))
    return out


def make_geom_wait(rng: np.random.Generator):
    """The reference's geometric waiting-time updater
    (grid_chain_sec11.py:147-148): Geometric(p) - 1 with
    p = |b_nodes| / (n_nodes ** n_parts - 1). Memoized per partition by the
    updater protocol — a rejected (self-loop) step re-reads the same sample,
    which is load-bearing for wait-sum statistics parity."""

    def geom(partition: Partition):
        nb = len(partition["b_nodes"])
        denom = partition.graph.n_nodes ** len(partition.parts) - 1
        p = nb / denom
        return int(rng.geometric(p)) - 1

    return geom


def make_boundary_slope(wall_of_edge):
    """Wall-cut-edge collector (grid_chain_sec11.py:55-78): returns the cut
    edges lying along the outer walls (and, for sec11, the four corner
    diagonals). ``wall_of_edge(u_label, v_label) -> int`` classifies; -1 is
    'not on a wall'. Returned deterministically ordered by canonical edge
    index (the reference returns ``list(set(...))`` — arbitrary order — and
    then consumes elements [0] and [1]; see kernel/metrics.py for how the
    vectorized path mirrors this deterministic choice)."""

    def slope(partition: Partition):
        g = partition.graph
        mask = partition.cut_edge_mask()
        out = []
        for e in np.nonzero(mask)[0]:
            u, v = g.labels[g.edges[e, 0]], g.labels[g.edges[e, 1]]
            if wall_of_edge(u, v) >= 0:
                out.append((u, v))
        return out

    return slope


def bnodes_p(partition: Partition) -> list:
    """The reference's 'boundary' updater (grid_chain_sec11.py:294-297):
    the frame-flagged node labels, recomputed every step there (an O(n)
    scan of a constant — here read off the graph's frame mask)."""
    g = partition.graph
    return [g.labels[i] for i in np.nonzero(g.frame_mask)[0]]


def step_num(partition: Partition) -> int:
    parent = partition.parent
    if not parent:
        return 0
    return parent["step_num"] + 1
