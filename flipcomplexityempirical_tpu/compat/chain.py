"""gerrychain-surface MarkovChain, constraints, proposals, acceptance.

Semantics pinned per SURVEY.md section 2.3 (consumed at
grid_chain_sec11.py:340-342,366):

- The chain yields ``total_steps`` states, the initial state first.
- An INVALID proposal is retried without consuming a step (the effective
  proposal distribution is uniform over *valid* moves; no Hastings
  correction is applied, faithfully to the reference).
- A VALID but rejected proposal consumes a step and yields the unchanged
  parent object — so memoized updater values (notably the geometric wait
  sample) are re-read, not recomputed.
- Before proposing, the current state's parent pointer is dropped
  (gerrychain's memory-leak truncation): acceptance functions may read one
  generation back, not two.
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional

import numpy as np

from .partition import Partition


class Validator:
    """Conjunction of constraints, short-circuit in listed order
    (grid_chain_sec11.py:340)."""

    def __init__(self, constraints: Iterable[Callable]):
        self.constraints = list(constraints)

    def __call__(self, partition: Partition) -> bool:
        return all(c(partition) for c in self.constraints)


def within_percent_of_ideal_population(initial_partition: Partition,
                                       percent: float = 0.01) -> Callable:
    """Bounds constraint built from the *initial* partition's tallies
    (grid_chain_sec11.py:319): every district population within
    [(1-p)*ideal, (1+p)*ideal], inclusive."""
    tallies = initial_partition["population"]
    ideal = sum(tallies.values()) / len(tallies)
    lo, hi = (1 - percent) * ideal, (1 + percent) * ideal

    def bounds(partition: Partition) -> bool:
        vals = partition["population"].values()
        return lo <= min(vals) and max(vals) <= hi

    return bounds


def single_flip_contiguous(partition: Partition) -> bool:
    """Exact single-flip contiguity: for each flipped node, its origin
    district (parent assignment) must remain connected after the flip.

    Correctness: the parent district was connected, so post-flip
    connectivity is equivalent to all of the flipped node's origin-district
    neighbors being mutually reachable within the shrunken district. A
    flipped node with no origin-district neighbors means the district was a
    singleton and is now empty — vacuously True here (population bounds are
    the reference's guard against vanishing districts)."""
    if not partition.flips or partition.parent is None:
        return contiguous(partition)
    g = partition.graph
    a = partition.assignment_array
    for lab in partition.flips:
        v = g.index[lab]
        old = int(partition.parent.assignment_array[v])
        d = int(g.deg[v])
        targets = [int(j) for j in g.nbr[v, :d] if a[j] == old]
        if len(targets) <= 1:
            continue
        # BFS within the origin district from one target to the rest
        seen = {targets[0]}
        frontier = [targets[0]]
        remaining = set(targets[1:])
        while frontier and remaining:
            nxt = []
            for i in frontier:
                di = int(g.deg[i])
                for j in g.nbr[i, :di]:
                    j = int(j)
                    if j not in seen and a[j] == old:
                        seen.add(j)
                        nxt.append(j)
                        remaining.discard(j)
            frontier = nxt
        if remaining:
            return False
    return True


def contiguous(partition: Partition) -> bool:
    """Full contiguity of every district (BFS per district)."""
    g = partition.graph
    a = partition.assignment_array
    for dist in set(int(x) for x in a):
        members = np.nonzero(a == dist)[0]
        seen = {int(members[0])}
        frontier = [int(members[0])]
        while frontier:
            nxt = []
            for i in frontier:
                di = int(g.deg[i])
                for j in g.nbr[i, :di]:
                    j = int(j)
                    if j not in seen and a[j] == dist:
                        seen.add(j)
                        nxt.append(j)
            frontier = nxt
        if len(seen) != len(members):
            return False
    return True


# ---------------------------------------------------------------------------
# Proposals (grid_chain_sec11.py:117-145; gerrychain.proposals surface)
# ---------------------------------------------------------------------------

def make_reversible_propose_bi(rng: np.random.Generator) -> Callable:
    """Uniform over the boundary-node set; flip the +1/-1 label
    (grid_chain_sec11.py:132-145). Requires the 'b_nodes' updater to return
    node labels (b_nodes_bi)."""

    def propose(partition: Partition) -> Partition:
        bn = sorted(partition["b_nodes"])
        fnode = bn[rng.integers(len(bn))]
        return partition.flip({fnode: -1 * partition.assignment[fnode]})

    return propose


def make_reversible_propose_pairs(rng: np.random.Generator) -> Callable:
    """k-district variant: uniform over (node, neighboring-part) pairs
    (grid_chain_sec11.py:117-130). Requires 'b_nodes' = b_nodes_pairs."""

    def propose(partition: Partition) -> Partition:
        bn = sorted(partition["b_nodes"])
        node, part = bn[rng.integers(len(bn))]
        return partition.flip({node: part})

    return propose


def make_random_flip(rng: np.random.Generator) -> Callable:
    """gerrychain.proposals.propose_random_flip (imported at
    grid_chain_sec11.py:24, unused there): pick a random cut edge, flip one
    endpoint to the other's district."""

    def propose(partition: Partition) -> Partition:
        ce = sorted(partition["cut_edges"])
        u, v = ce[rng.integers(len(ce))]
        if rng.integers(2):
            u, v = v, u
        return partition.flip({u: partition.assignment[v]})

    return propose


def go_nowhere(partition: Partition) -> Partition:
    return partition.flip({})


def always_accept(partition: Partition) -> bool:
    return True


def make_cut_accept(rng: np.random.Generator, base_key: str = "base") -> Callable:
    """The reference's literal acceptance (grid_chain_sec11.py:171-179):
    accept iff U < base**(-|cut(child)| + |cut(parent)|). Deliberately omits
    the |b_nodes| proposal-asymmetry correction, exactly as the reference
    does — see make_corrected_cut_accept for the reversible version."""

    def accept(partition: Partition) -> bool:
        bound = 1.0
        if partition.parent is not None:
            delta = (-len(partition["cut_edges"])
                     + len(partition.parent["cut_edges"]))
            bound = partition[base_key] ** delta
        return rng.random() < bound

    return accept


def make_corrected_cut_accept(rng: np.random.Generator,
                              base_key: str = "base") -> Callable:
    """Reversibility-corrected acceptance: multiplies the Metropolis bound by
    |b_nodes(parent)| / |b_nodes(child)| — the correction the reference's
    dead annealing_cut_accept_backwards carries (grid_chain_sec11.py:99) and
    cut_accept lacks. With it the chain is reversible w.r.t.
    pi ∝ base^(-|cut|) restricted to valid states (up to the invalid-move
    conditioning)."""

    def accept(partition: Partition) -> bool:
        bound = 1.0
        if partition.parent is not None:
            delta = (-len(partition["cut_edges"])
                     + len(partition.parent["cut_edges"]))
            ratio = (len(partition.parent["b_nodes"])
                     / len(partition["b_nodes"]))
            bound = partition[base_key] ** delta * ratio
        return rng.random() < bound

    return accept


def make_fixed_endpoints(pairs=(((19, 0), (20, 0)), ((19, 39), (20, 39)))):
    """The reference's fixed_endpoints predicate (grid_chain_sec11.py:39-40):
    the interface endpoints stay pinned — each listed label pair must
    straddle the district boundary."""

    def fixed_endpoints(partition: Partition) -> bool:
        return all(partition.assignment[a] != partition.assignment[b]
                   for (a, b) in pairs)

    return fixed_endpoints


def boundary_condition(partition: Partition) -> bool:
    """grid_chain_sec11.py:43-52: True iff the outer-frame nodes (the
    'boundary' updater list) do not all lie in one district — i.e. the
    interface touches the frame."""
    blist = partition["boundary"]
    o_part = partition.assignment[blist[0]]
    return any(partition.assignment[x] != o_part for x in blist)


def make_uniform_accept(rng: np.random.Generator, popbound: Callable):
    """grid_chain_sec11.py:159-165: accept iff popbound ∧
    single_flip_contiguous ∧ boundary_condition (target: uniform over that
    constrained set). Note the reference re-checks validity here even though
    the Validator already did — preserved for parity."""

    def accept(partition: Partition) -> bool:
        bound = 0.0
        if (popbound(partition) and single_flip_contiguous(partition)
                and boundary_condition(partition)):
            bound = 1.0
        return rng.random() < bound

    return accept


def linear_beta_schedule(t0: float = 100000.0, ramp: float = 100000.0,
                         beta_max: float = 3.0) -> Callable:
    """The commented-out annealing schedule of grid_chain_sec11.py:88-95:
    beta = 0 for t < t0, then (t - t0)/ramp, capped at beta_max."""

    def beta(t: int) -> float:
        return float(np.clip((t - t0) / ramp, 0.0, beta_max))

    return beta


def make_annealing_cut_accept_backwards(
        rng: np.random.Generator, popbound: Callable, base: float = 0.1,
        beta=5.0) -> Callable:
    """grid_chain_sec11.py:81-110 (dead code there; an option here): the
    boundary-ratio-corrected Metropolis acceptance
    base**(beta * -dcut) * |b(child)| / |b(parent)| with inline population
    and contiguity re-checks. ``beta`` is a constant or a callable of
    partition["step_num"] (see linear_beta_schedule). Note the correction
    direction is the reference's literal len(boundaries1)/len(boundaries2) =
    child/parent — the INVERSE of the reversibility correction in
    make_corrected_cut_accept — preserved verbatim."""

    def accept(partition: Partition) -> bool:
        bound = 1.0
        if partition.parent is not None:
            b = beta(partition["step_num"]) if callable(beta) else beta
            boundaries1 = {x for e in partition["cut_edges"] for x in e}
            boundaries2 = {x for e in partition.parent["cut_edges"]
                           for x in e}
            delta = (-len(partition["cut_edges"])
                     + len(partition.parent["cut_edges"]))
            bound = (base ** (b * delta)) * (len(boundaries1)
                                             / len(boundaries2))
            if not popbound(partition):
                bound = 0.0
            if not single_flip_contiguous(partition):
                bound = 0.0
        return rng.random() < bound

    return accept


class MarkovChain:
    def __init__(self, proposal: Callable, constraints: Callable,
                 accept: Callable, initial_state: Partition,
                 total_steps: int):
        self.proposal = proposal
        self.is_valid = constraints
        self.accept = accept
        self.initial_state = initial_state
        self.total_steps = total_steps
        self.state: Optional[Partition] = None
        self.counter = 0

    def __len__(self):
        return self.total_steps

    def __iter__(self):
        self.counter = 0
        self.state = self.initial_state
        return self

    def __next__(self) -> Partition:
        if self.counter == 0:
            self.counter += 1
            return self.state
        while self.counter < self.total_steps:
            # memory-leak truncation: acceptance may read one generation back
            self.state.parent = None
            proposed = self.proposal(self.state)
            if self.is_valid(proposed):
                if self.accept(proposed):
                    self.state = proposed
                self.counter += 1
                return self.state
        raise StopIteration
