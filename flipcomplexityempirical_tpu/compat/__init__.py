"""Pure-Python compatibility layer: the gerrychain surface the reference
consumes (SURVEY.md section 2.3), re-implemented over the array substrate.

Serves three roles: (a) the oracle the vectorized JAX kernel is validated
against, (b) the ``backend="python"`` path of the experiment driver, and
(c) a migration surface for reference users whose code speaks
Partition/MarkovChain."""

from .partition import (
    Partition, Tally, Election, ElectionResults, cut_edges, b_nodes_bi,
    b_nodes_pairs, make_geom_wait, make_boundary_slope, step_num, bnodes_p,
    mean_median, efficiency_gap,
)
from .recom import make_recom, random_spanning_tree, bipartition_tree
from .chain import (
    MarkovChain, Validator, within_percent_of_ideal_population,
    single_flip_contiguous, contiguous,
    make_reversible_propose_bi, make_reversible_propose_pairs,
    make_random_flip, go_nowhere, always_accept,
    make_cut_accept, make_corrected_cut_accept,
    make_fixed_endpoints, boundary_condition, make_uniform_accept,
    linear_beta_schedule, make_annealing_cut_accept_backwards,
)

__all__ = [
    "Partition", "Tally", "Election", "ElectionResults",
    "mean_median", "efficiency_gap",
    "cut_edges", "b_nodes_bi", "b_nodes_pairs",
    "make_geom_wait", "make_boundary_slope", "step_num", "bnodes_p",
    "MarkovChain", "Validator", "within_percent_of_ideal_population",
    "single_flip_contiguous", "contiguous",
    "make_reversible_propose_bi", "make_reversible_propose_pairs",
    "make_random_flip", "go_nowhere", "always_accept",
    "make_cut_accept", "make_corrected_cut_accept",
    "make_fixed_endpoints", "boundary_condition", "make_uniform_accept",
    "linear_beta_schedule", "make_annealing_cut_accept_backwards",
    "make_recom", "random_spanning_tree", "bipartition_tree",
]
