"""ReCom (recombination) spanning-tree proposal — the gerrychain surface the
reference constructs but never wires into a chain (grid_chain_sec11.py:
328-335: ``partial(recom, pop_col="population", pop_target=ideal,
epsilon=0.05, node_repeats=1)``; a live capability target per SURVEY.md
section 2.2 row 21 and the BASELINE.json config lineage).

Semantics (gerrychain ~0.2.x recom):
1. pick a uniformly random cut edge; the two districts it straddles merge;
2. draw a random spanning tree of the merged induced subgraph (random iid
   edge weights -> minimum spanning tree, gerrychain's
   ``random_spanning_tree``);
3. find a tree edge whose removal splits the merged region into two sides
   each within ``epsilon * pop_target`` of ``pop_target`` (gerrychain's
   ``bipartition_tree``), retrying with a fresh tree up to ``node_repeats``
   times per cut edge;
4. reassign the two sides to the two district labels.

Both split sides are connected by construction (each is a subtree), so no
contiguity check is needed on recom moves.

The batched TPU implementation of the same move is sampling/recom.py; this
host version is its oracle and the ``backend="python"`` path.
"""

from __future__ import annotations

from functools import partial  # noqa: F401  (mirrors the reference import)
from typing import Callable, Optional

import numpy as np

from .partition import Partition


class _UnionFind:
    def __init__(self, n: int):
        self.parent = list(range(n))

    def find(self, x: int) -> int:
        while self.parent[x] != x:
            self.parent[x] = self.parent[self.parent[x]]
            x = self.parent[x]
        return x

    def union(self, a: int, b: int) -> bool:
        ra, rb = self.find(a), self.find(b)
        if ra == rb:
            return False
        self.parent[ra] = rb
        return True


def random_spanning_tree(graph, nodes: np.ndarray,
                         rng: np.random.Generator) -> list:
    """Random-weight MST of the subgraph induced by ``nodes`` (index array):
    iid uniform edge weights + Kruskal — gerrychain's tree distribution.
    Returns a list of edge-index pairs (u, v). Raises if the induced
    subgraph is disconnected (cannot happen for a merged district pair)."""
    member = np.zeros(graph.n_nodes, dtype=bool)
    member[nodes] = True
    eu, ev = graph.edges[:, 0], graph.edges[:, 1]
    internal = np.nonzero(member[eu] & member[ev])[0]
    order = internal[np.argsort(rng.random(len(internal)))]
    uf = _UnionFind(graph.n_nodes)
    tree = []
    need = len(nodes) - 1
    for ei in order:
        u, v = int(eu[ei]), int(ev[ei])
        if uf.union(u, v):
            tree.append((u, v))
            if len(tree) == need:
                break
    if len(tree) != need:
        raise ValueError("induced subgraph is disconnected")
    return tree


def bipartition_tree(graph, nodes: np.ndarray, pop: np.ndarray,
                     pop_target: float, epsilon: float,
                     rng: np.random.Generator,
                     max_attempts: int = 1000) -> Optional[np.ndarray]:
    """Split ``nodes`` into two connected sides with populations within
    ``epsilon * pop_target`` of ``pop_target`` by cutting one edge of a
    random spanning tree. A tree with no balanced edge is redrawn, up to
    ``max_attempts`` trees (gerrychain's bipartition_tree loops unbounded;
    the cap here trades a hang for a None return). Returns the node-index
    array of one side, or None."""
    total = float(pop[nodes].sum())
    lo, hi = pop_target * (1 - epsilon), pop_target * (1 + epsilon)
    if not (2 * lo <= total <= 2 * hi):
        return None  # no tree edge can balance an infeasible total
    for _ in range(max(1, max_attempts)):
        tree = random_spanning_tree(graph, nodes, rng)
        adj: dict[int, list[int]] = {int(x): [] for x in nodes}
        for (u, v) in tree:
            adj[u].append(v)
            adj[v].append(u)
        # iterative post-order from an arbitrary root: subtree populations
        root = int(nodes[0])
        parent = {root: -1}
        order = [root]
        stack = [root]
        while stack:
            x = stack.pop()
            for y in adj[x]:
                if y not in parent:
                    parent[y] = x
                    order.append(y)
                    stack.append(y)
        sub = {x: float(pop[x]) for x in parent}
        for x in reversed(order[1:]):
            sub[parent[x]] += sub[x]
        balanced = [x for x in order[1:]
                    if lo <= sub[x] <= hi and lo <= total - sub[x] <= hi]
        if not balanced:
            continue
        cut_child = balanced[rng.integers(len(balanced))]
        # the chosen side = the subtree under cut_child (children of x are
        # exactly the tree neighbors whose parent is x)
        side = []
        stack = [cut_child]
        while stack:
            x = stack.pop()
            side.append(x)
            stack.extend(y for y in adj[x] if parent[y] == x)
        return np.asarray(side, dtype=np.int64)
    return None


def make_recom(rng: np.random.Generator, pop_col: str = "population",
               pop_target: Optional[float] = None, epsilon: float = 0.05,
               node_repeats: int = 1) -> Callable:
    """The proposal factory matching the reference's partial(recom, ...)
    call shape (grid_chain_sec11.py:330-335). ``pop_target`` defaults to
    half the merged pair's population. ``node_repeats`` scales the
    tree-redraw budget (node_repeats * 1000 attempts, approximating
    gerrychain's unbounded redraw loop); exhausting it degrades to the
    identity move, keeping total-step semantics intact.

    Population weights come from the graph's ``pop`` array (what
    Tally('population') tallies); other columns are not wired up, and a
    different ``pop_col`` raises rather than silently balancing the wrong
    quantity."""
    if pop_col != "population":
        raise ValueError(
            f"pop_col {pop_col!r} is not supported: balancing uses the "
            "graph's pop array (the 'population' column)")

    def propose(partition: Partition) -> Partition:
        g = partition.graph
        a = partition.assignment_array
        mask = partition.cut_edge_mask()
        cut_ids = np.nonzero(mask)[0]
        if len(cut_ids) == 0:
            return partition.flip({})
        e = int(cut_ids[rng.integers(len(cut_ids))])
        u, v = int(g.edges[e, 0]), int(g.edges[e, 1])
        d1, d2 = int(a[u]), int(a[v])
        nodes = np.nonzero((a == d1) | (a == d2))[0]
        # per-node weights come from the graph metadata, same as Tally
        # (pop_col exists for call-shape parity with the reference partial)
        pop = np.asarray(g.pop, dtype=np.float64)
        target = (pop_target if pop_target is not None
                  else float(pop[nodes].sum()) / 2.0)
        side = bipartition_tree(g, nodes, pop, target, epsilon, rng,
                                max_attempts=max(1, node_repeats) * 1000)
        if side is None:
            return partition.flip({})
        in_side = np.zeros(g.n_nodes, dtype=bool)
        in_side[side] = True
        flips = {}
        for x in nodes:
            newd = d1 if in_side[x] else d2
            if int(a[x]) != newd:
                flips[g.labels[x]] = newd
        return partition.flip(flips)

    return propose
