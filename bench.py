#!/usr/bin/env python
"""Headline benchmark: aggregate flip throughput on the BASELINE workload.

Workload (BASELINE.json north star): 2-district single-node-flip chains on a
64x64 grid, full reference semantics (boundary proposal, re-propose-on-
invalid, patch contiguity, population bounds, Metropolis accept, geometric
waits, parity metric bookkeeping). Target: >=1e4 chains at >=1e7 aggregate
flips/sec on a v5e-8 — i.e. >=1.25e6 flips/sec/chip, which is the
vs_baseline denominator here (this box exposes one chip).

Routes through the board (stencil) fast path when
``kernel.board.supports(graph, spec)`` holds — tests/test_board.py proves it
distribution-identical to the general path — and falls back to the general
gather/while_loop kernel otherwise (``--general`` forces the fallback).
On the real chip the default chain count resolves to 8192, the measured
single-chip throughput peak (PROFILE.md chain-count sweep); explicit
``--chains`` always wins.

Prints exactly one JSON line on stdout:
  {"metric": ..., "value": N, "unit": "flips/s", "vs_baseline": N,
   "device": ..., "path": ..., "repeats": N, "repeat_policy": "best",
   ["body": ...,] ["cpu_fallback": true]}
When the accelerator probe fails the measurement still happens, on host
CPU, tagged "device": "cpu-fallback" and "cpu_fallback": true, with
vs_baseline null (a host number is not comparable to the per-chip TPU
target). The fallback configuration is FROZEN for cross-round
comparability (VERDICT r4): chains=256 (the measured host sweet spot),
steps/warmup/chunk at their defaults, repeats=2, best-of policy. Do not
retune it — a fallback record is only interpretable against earlier
fallback records if the configuration never moves (BENCH_r04.json is the
first record under this configuration; being pre-schema-change it still
carries a numeric vs_baseline — read its "value" and ignore that ratio). Per-run detail (chains, seconds,
accept rate) goes to stderr as a second JSON object.
"""

import argparse
import json
import os
import sys
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--grid", type=int, default=64)
    ap.add_argument("--graph", choices=["square", "sec11", "frank", "hex"],
                    default="square",
                    help="workload graph: 'square' is the headline "
                         "--grid x --grid rook grid; 'sec11' / 'frank' "
                         "are the paper's corner-surgery grid and "
                         "Frankengraph, which the lowering pass "
                         "(flipcomplexityempirical_tpu/lower) compiles "
                         "onto the board path's lowered stencil body "
                         "(k=2 bi walk only); 'hex' is a --grid x --grid "
                         "hexagonal lattice — off the board path, so it "
                         "races the rejection-free general_dense kernel "
                         "against the legacy general kernel and reports "
                         "the faster (ISSUE 15)")
    ap.add_argument("--chains", type=int, default=None,
                    help="chain count; explicit values always win. "
                         "Default resolves to 8192 on the chip for the "
                         "k=2 board-path headline (the measured "
                         "single-chip peak, PROFILE.md sweep), 4096 for "
                         "the pallas/general paths and k>2 pair walks "
                         "(the shape their committed records used), and "
                         "256 on cpu-fallback (frozen, see module "
                         "docstring)")
    ap.add_argument("--steps", type=int, default=3001)
    ap.add_argument("--warmup", type=int, default=501)
    ap.add_argument("--chunk", type=int, default=500,
                    help="scan length; must divide steps-1 and warmup-1 so "
                         "warmup and timed runs share one compiled kernel")
    ap.add_argument("--base", type=float, default=2.63815853)
    ap.add_argument("--pop-tol", type=float, default=0.1)
    ap.add_argument("--k", type=int, default=2,
                    help="number of districts; k=2 runs the headline "
                         "2-district bi walk, k>2 switches to the "
                         "k-district pair walk (BASELINE config 2) on a "
                         "k-stripes initial plan; the metric name and "
                         "vs_baseline keep their per-chip flip meaning")
    ap.add_argument("--cpu", action="store_true", help="force CPU backend")
    ap.add_argument("--mesh", type=int, default=None, metavar="N",
                    help="multi-chip mode: build an N-device chains mesh "
                         "(distribute.make_mesh), run the 1/2/4/.../N "
                         "scaling ladder through the sharded board train "
                         "step (replica exchange over ICI each --chunk "
                         "steps), and emit a MULTICHIP record with "
                         "aggregate AND per-chip flips/s plus the scaling "
                         "table. --chains means chains PER CHIP here "
                         "(weak scaling). On the CPU backend the N "
                         "devices are forced host devices "
                         "(--xla_force_host_platform_device_count), so "
                         "the mesh path runs without silicon")
    ap.add_argument("--general", action="store_true",
                    help="force the general (gather) path even when the "
                         "board fast path supports the workload")
    ap.add_argument("--pallas", action="store_true",
                    help="route through the Pallas VMEM-resident kernel "
                         "(kernel/pallas_board.py) instead of the XLA "
                         "board path")
    ap.add_argument("--body", choices=["int8", "bits"], default=None,
                    help="force ONE board body instead of timing both "
                         "and reporting the faster (for per-body "
                         "records, e.g. the v4-vs-v5 on-chip comparison). "
                         "On the rook grid 'bits' is the bit-board and "
                         "'int8' the plane body; on sec11/frank (the "
                         "lowered stencil family) 'bits' is the packed "
                         "lowered_bits body and 'int8' the int8 lowered "
                         "body. Board path only, incompatible with "
                         "--pallas/--general")
    ap.add_argument("--block-chains", type=int, default=128)
    ap.add_argument("--profile", metavar="DIR", default=None,
                    help="wrap the timed region in a jax.profiler trace "
                         "written to DIR (SURVEY.md section 5 tracing; "
                         "the shared obs.profile_region hook)")
    ap.add_argument("--events", metavar="PATH", default=None,
                    help="append structured telemetry (obs JSONL: "
                         "run_start/chunk/compile/run_end with per-chunk "
                         "flips/s, accept rate, transfer bytes) to PATH; "
                         "'-' streams to stderr. Fold with "
                         "tools/obs_report.py. The default null recorder "
                         "keeps the timed region un-instrumented")
    ap.add_argument("--repeats", type=int, default=None,
                    help="timed-region repetitions; the reported rate is "
                         "the best (throughput benchmarks should not be "
                         "charged for transient device/tunnel stalls). "
                         "Default 2, or 1 under --profile so the trace "
                         "holds exactly the timed region")
    ap.add_argument("--ess", action="store_true",
                    help="also run a recorded pass and report effective "
                         "samples of the cut-count trajectory per second "
                         "of wall clock (the BASELINE metric's "
                         "'wall-clock to target ESS' axis) on stderr")
    ap.add_argument("--devstats", action="store_true",
                    help="also run two recorded legs at the winning "
                         "variant — the flagged history oracle path vs "
                         "device-resident analytics "
                         "(stats.accumulators) — and report per-step "
                         "readback bytes for both plus the "
                         "summary-vs-history ratio as a "
                         "'readback_summary_vs_history_ratio' record "
                         "(higher is better) qualified per "
                         "[path,kernel_path]. Board/general paths only "
                         "(not --pallas)")
    ap.add_argument("--record-every", type=int, default=1,
                    help="history thinning for the --ess recorded pass "
                         "(device-side stride; cuts the history readback "
                         "by the factor at large chain counts)")
    ap.add_argument("--service", action="store_true",
                    help="measure the sweep service instead of a raw "
                         "kernel: --tenants coalescible jobs drained as "
                         "one batch vs a solo tenant, reported as a "
                         "'tenant_efficiency' record (per-tenant "
                         "end-to-end throughput ratio, compile "
                         "included — the coalescing win is one compile "
                         "serving every tenant). Incompatible with the "
                         "kernel-path flags; --chains means chains PER "
                         "TENANT (default 2) and --graph picks the "
                         "tenant family (sec11/frank; square maps to "
                         "frank)")
    ap.add_argument("--tenants", type=int, default=4,
                    help="--service: coalescible tenants sharing the "
                         "device")
    ap.add_argument("--adaptive", action="store_true",
                    help="measure the adaptive control plane instead of "
                         "a raw kernel: one small seeded sweep (two "
                         "frank configs + one tempered) run twice — "
                         "adaptive (control/ EarlyStop+Ladder policies, "
                         "run FIRST so it pays the cold compiles) vs the "
                         "fixed schedule — reported as a "
                         "'wall_clock_to_target_ess' record (ratio of "
                         "fixed to adaptive wall clock; > 1 means the "
                         "control loop reached the diagnostic targets "
                         "in strictly less wall clock). --steps is the "
                         "fixed schedule length, --chains the chains "
                         "per config")
    ap.add_argument("--target-rhat", type=float, default=1.5,
                    help="--adaptive: split R-hat early-stop target")
    ap.add_argument("--target-ess", type=float, default=64.0,
                    help="--adaptive: total-ESS early-stop target")
    ap.add_argument("--workload-matrix", action="store_true",
                    help="benchmark the workload catalog instead of one "
                         "kernel: each named workload (workloads/"
                         "catalog.py) runs its tuned shape through the "
                         "driver — flip and ReCom chain families, "
                         "dual-graph fixtures, proposal variants — and "
                         "emits one per-family record qualified by "
                         "workload name, so bench_compare gates "
                         "[workload=...] metrics without cross-family "
                         "interference")
    ap.add_argument("--workloads", metavar="NAMES", default=None,
                    help="--workload-matrix: comma-separated workload "
                         "names to run (default: a tier-1-sized spread "
                         "across the chain families and variants)")
    ap.add_argument("--fleet", action="store_true",
                    help="measure the fleet admission design instead "
                         "of a kernel: tools/loadtest.py's virtual-"
                         "clock simulation over the server's own "
                         "TokenBucket + FairAdmission, reported as a "
                         "'fleet_fairness_jain' record (+ p50/p99 "
                         "queue-to-start). The scenario is FROZEN at "
                         "the ROADMAP target shape (500 tenants x 2 "
                         "jobs, 16 workers, ~25% utilization) for "
                         "cross-round comparability; scheduling only, "
                         "no device work — always cpu-tagged")
    ap.add_argument("--ess-host", action="store_true",
                    help="force the host-copy f64 ESS estimator for the "
                         "--ess recorded pass (streams the history to "
                         "host per chunk instead of holding it "
                         "device-resident; use for horizons whose "
                         "(chains, steps) x 4-key f32 history would not "
                         "fit HBM)")
    args = ap.parse_args()
    if args.fleet:
        for flag, name in ((args.pallas, "--pallas"),
                           (args.general, "--general"),
                           (args.ess, "--ess"),
                           (args.mesh is not None, "--mesh"),
                           (args.body is not None, "--body"),
                           (args.service, "--service"),
                           (args.adaptive, "--adaptive"),
                           (args.workload_matrix, "--workload-matrix")):
            if flag:
                ap.error(f"{name} is incompatible with --fleet (the "
                         "fleet benchmark simulates admission "
                         "scheduling, not device work)")
        _fleet_bench(args)
        return
    if args.service:
        for flag, name in ((args.pallas, "--pallas"),
                           (args.general, "--general"),
                           (args.ess, "--ess"),
                           (args.mesh is not None, "--mesh"),
                           (args.body is not None, "--body"),
                           (args.adaptive, "--adaptive"),
                           (args.workload_matrix, "--workload-matrix")):
            if flag:
                ap.error(f"{name} is incompatible with --service (the "
                         "service benchmark drives whole sweep jobs, "
                         "not one kernel path)")
        _service_bench(args)
        return
    if args.adaptive:
        for flag, name in ((args.pallas, "--pallas"),
                           (args.general, "--general"),
                           (args.ess, "--ess"),
                           (args.mesh is not None, "--mesh"),
                           (args.body is not None, "--body"),
                           (args.service, "--service"),
                           (args.workload_matrix, "--workload-matrix")):
            if flag:
                ap.error(f"{name} is incompatible with --adaptive (the "
                         "adaptive benchmark drives whole sweep jobs "
                         "through the control loop, not one kernel "
                         "path)")
        _adaptive_bench(args)
        return
    if args.workload_matrix:
        for flag, name in ((args.pallas, "--pallas"),
                           (args.general, "--general"),
                           (args.ess, "--ess"),
                           (args.mesh is not None, "--mesh"),
                           (args.body is not None, "--body"),
                           (args.service, "--service"),
                           (args.adaptive, "--adaptive")):
            if flag:
                ap.error(f"{name} is incompatible with --workload-matrix "
                         "(the matrix drives whole catalog workloads "
                         "through the driver, not one kernel path)")
        _workload_matrix_bench(args)
        return
    if ((args.steps - 1) % args.chunk or (args.warmup - 1) % args.chunk
            or args.warmup - 1 < args.chunk):
        ap.error(f"--chunk {args.chunk} must divide steps-1 "
                 f"({args.steps - 1}) and warmup-1 ({args.warmup - 1}), and "
                 f"warmup-1 must be >= chunk, so the warmup actually "
                 "compiles the chunk-length kernel the timed region reuses")
    if args.record_every > 1 and args.chunk % args.record_every:
        ap.error(f"--record-every {args.record_every} must divide --chunk "
                 f"({args.chunk}): the runner would otherwise snap the "
                 "chunk down and compile a fresh partial-chunk kernel "
                 "inside the timed ESS window")

    cpu_fallback = False
    if not args.cpu:
        # probe the accelerator in a subprocess (a hung device claim would
        # otherwise stall this process for the caller's full timeout, and
        # probing in-process would pin our backend choice). On failure,
        # fall back to an EXPLICIT CPU measurement rather than exiting
        # empty-handed: a round's benchmark record must never be null just
        # because the device tunnel is down (round-3 post-mortem).
        import subprocess
        err = b""
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax, sys; "
                 "sys.exit(jax.devices()[0].platform == 'cpu')"],
                timeout=120, capture_output=True)
            ok = probe.returncode == 0
            err = probe.stderr
        except subprocess.TimeoutExpired:
            ok = False
        if not ok:
            tail = err.decode(errors="replace").strip().splitlines()[-3:]
            for line in tail:
                print(f"bench probe: {line}", file=sys.stderr)
            if args.pallas:
                # the Pallas path only exists compiled (interpret mode is
                # a test vehicle ~1000x too slow to measure); a CPU
                # stand-in would crash in pallas_call, so fail cleanly
                # instead of emitting a traceback (observed when the
                # tunnel dropped between a capture and its rerun)
                print("bench: --pallas requires the accelerator; no CPU "
                      "fallback exists for the compiled Pallas kernel",
                      file=sys.stderr)
                sys.exit(3)
            print("bench: accelerator backend unreachable or fell back "
                  "to CPU (device probe); emitting a CPU-tagged "
                  "measurement (the TPU number this stands in for is NOT "
                  "comparable to vs_baseline's per-chip target)",
                  file=sys.stderr)
            cpu_fallback = True
            args.cpu = True
            if args.chains is None:
                # keep the fallback's wall clock tolerable: fewer chains,
                # same per-chain horizon; the JSON carries the real count.
                # 256 is the measured host-CPU throughput sweet spot
                # (134k flips/s vs 115k at 512 on this box)
                args.chains = 256

    if args.mesh is not None:
        if args.mesh < 1:
            ap.error("--mesh needs N >= 1")
        for flag, name in ((args.pallas, "--pallas"), (args.ess, "--ess"),
                           (args.general, "--general")):
            if flag:
                print(f"bench: {name} is incompatible with --mesh (the "
                      "sharded benchmark routes through the board fast "
                      "path's train step)", file=sys.stderr)
                sys.exit(2)
        if args.cpu:
            # the forced-host device count must be pinned BEFORE jax
            # imports (backend init reads XLA_FLAGS once); keep a larger
            # pre-set count, grow a smaller one
            import re
            flags = os.environ.get("XLA_FLAGS", "")
            m = re.search(r"--xla_force_host_platform_device_count=(\d+)",
                          flags)
            if m is None or int(m.group(1)) < args.mesh:
                flags = re.sub(
                    r"--xla_force_host_platform_device_count=\d+", "",
                    flags)
                os.environ["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count"
                    f"={args.mesh}").strip()

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import flipcomplexityempirical_tpu as fce
    from flipcomplexityempirical_tpu import obs
    from flipcomplexityempirical_tpu.kernel import board as kboard

    if args.mesh is not None and len(jax.devices()) < args.mesh:
        print(f"bench: --mesh {args.mesh} needs {args.mesh} devices, "
              f"backend exposes {len(jax.devices())}", file=sys.stderr)
        sys.exit(2)

    if args.mesh is not None:
        # per-host event sink: a multi-host mesh writes
        # events.host<K>.jsonl per host (trace_export merges them);
        # single-host runs get the plain path
        from flipcomplexityempirical_tpu.distribute import host_recorder
        rec = host_recorder(args.events)
    else:
        rec = obs.from_spec(args.events)

    if args.graph in ("sec11", "frank") and args.k != 2:
        print("bench: --graph sec11/frank runs the reference 2-district "
              "bi walk; drop --k", file=sys.stderr)
        sys.exit(2)
    if args.graph == "sec11":
        g = fce.graphs.grid_sec11()
        plan = fce.graphs.sec11_plan(g, alignment=0)
    elif args.graph == "frank":
        g = fce.graphs.frankengraph()
        plan = fce.graphs.frank_plan(g, alignment=0)
    elif args.graph == "hex":
        g = fce.graphs.hex_lattice(args.grid, args.grid)
        plan = fce.graphs.stripes_plan(g, args.k)
    else:
        g = fce.graphs.square_grid(args.grid, args.grid)
        plan = fce.graphs.stripes_plan(g, args.k)
    spec = fce.Spec(n_districts=args.k,
                    proposal=("bi" if args.k == 2 else "pair"),
                    contiguity="patch",
                    invalid="repropose", accept="cut",
                    parity_metrics=True, geom_waits=True,
                    record_interface=False)

    if args.body is not None and (args.pallas or args.general):
        print("bench: --body selects a board-path body; it cannot be "
              "combined with --pallas or --general", file=sys.stderr)
        sys.exit(2)
    if args.pallas and args.cpu:
        print("bench: --pallas cannot run on the CPU backend (pallas_call "
              "supports interpret mode only there, which is not a "
              "measurement)", file=sys.stderr)
        sys.exit(2)
    if args.pallas and args.k != 2:
        print("bench: the pallas path serves the 2-district bi walk only "
              "(kernel/pallas_board.py check()); drop --pallas or --k",
              file=sys.stderr)
        sys.exit(2)
    if args.pallas and args.graph != "square":
        print("bench: the pallas kernel hardcodes the plain rook stencil; "
              "sec11/frank run the lowered stencil body (drop --pallas)",
              file=sys.stderr)
        sys.exit(2)

    use_board = kboard.supports(g, spec) and not args.general
    if args.body is not None and not use_board:
        print("bench: --body given but the board path does not support "
              "this workload", file=sys.stderr)
        sys.exit(2)
    if args.mesh is not None:
        if not use_board:
            print("bench: --mesh requires a board-path workload "
                  "(kernel.board.supports rejects this graph/spec)",
                  file=sys.stderr)
            sys.exit(2)
        _mesh_bench(args, cpu_fallback, g, plan, spec, rec)
        rec.close()
        return

    if args.chains is None:
        # on the real chip the k=2 board path's measured throughput peak
        # is C=8192 (20.45M flips/s vs 18.47M at 4096; full chain-count
        # sweep in PROFILE.md) — record the headline at the best
        # single-chip configuration. Every other path/workload keeps
        # 4096, the shape its committed records used.
        args.chains = (8192 if use_board and args.k == 2
                       and not args.pallas and not args.cpu else 4096)
    variants = [None]
    if use_board:
        bg, states, params = fce.sampling.init_board(
            g, plan, n_chains=args.chains, seed=0, spec=spec,
            base=args.base, pop_tol=args.pop_tol)

        if args.pallas:
            def run(states, n_steps, variant=None, record=False):
                return fce.sampling.run_board_pallas(
                    bg, spec, params, states, n_steps=n_steps,
                    record_history=record, chunk=args.chunk,
                    block_chains=args.block_chains)
        else:
            from flipcomplexityempirical_tpu.kernel import bitboard
            # 'lowered' here mirrors run_board_chunk's own branch: a
            # surgical/interface workload runs the stencil family, so
            # --body / the two-variant race selects lowered_bits vs
            # lowered instead of bitboard vs int8
            lowered = bg.surgical or spec.record_interface
            bits_ok = (bitboard.supported_lowered(bg, spec) if lowered
                       else (bitboard.supported(bg, spec)
                             or bitboard.supported_pair(bg, spec)))
            if args.body is not None:
                if args.body == "bits" and not bits_ok:
                    print("bench: --body bits unsupported for this "
                          "workload", file=sys.stderr)
                    sys.exit(2)
                variants = [args.body == "bits"]
            elif bits_ok:
                # the bit-packed and int8 bodies are bit-identical; time
                # BOTH and report the faster (which body wins is a pure
                # hardware/compiler question the benchmark answers)
                variants = [True, False]

            def run(states, n_steps, variant=None, record=False,
                    device_hist=False, analytics=None, recorder=rec):
                return fce.sampling.run_board(
                    bg, spec, params, states, n_steps=n_steps,
                    record_history=record, chunk=args.chunk, bits=variant,
                    record_every=args.record_every if record else 1,
                    history_device=device_hist, recorder=recorder,
                    analytics=analytics)
    else:
        from flipcomplexityempirical_tpu.kernel import dense as kdense
        dg, states, params = fce.init_batch(
            g, plan, n_chains=args.chains, seed=0, spec=spec,
            base=args.base, pop_tol=args.pop_tol)

        if args.general:
            variants = ["general"]
        elif kdense.supported(g, spec):
            # the rejection-free dense body and the legacy re-propose loop
            # serve the same distribution (not bit-identically — see
            # tests/test_dense.py's exact-enumeration gate); time BOTH and
            # report the faster, mirroring the board path's body race
            variants = ["general_dense", "general"]

        def run(states, n_steps, variant=None, record=False,
                device_hist=False, analytics=None, recorder=rec):
            return fce.run_chains(
                dg, spec, params, states, n_steps=n_steps,
                record_history=record, chunk=args.chunk,
                record_every=args.record_every if record else 1,
                history_device=device_hist, recorder=recorder,
                kernel_path=variant, analytics=analytics)

    # compile + mix in (reach steady-state boundary sizes); same chunk as
    # the timed run so the timed region reuses the compiled kernel
    from flipcomplexityempirical_tpu.resilience import degrade as rdegrade
    degrade_mark = rdegrade.snapshot()
    res = run(states, args.warmup, variants[0])
    states = res.state
    # zero telemetry so rates below cover only the timed steps
    import jax.numpy as jnp
    states = states.replace(
        accept_count=jnp.zeros_like(states.accept_count),
        tries_sum=jnp.zeros_like(states.tries_sum),
        exhausted_count=jnp.zeros_like(states.exhausted_count))
    jax.block_until_ready(jax.tree.leaves(states)[0])

    for variant in variants[1:]:
        # compile the other variants BEFORE the profiled/timed region
        jax.block_until_ready(
            jax.tree.leaves(run(states, args.warmup, variant).state)[0])

    if args.profile:
        # one body only under --profile, so the trace holds exactly one
        # kernel's timed region (the auto-dispatched body)
        variants = variants[:1]
    prof = obs.profile_region(args.profile)
    repeats = args.repeats if args.repeats else (1 if args.profile else 2)
    dt = float("inf")
    best = variants[0]
    with prof:
        for variant in variants:
            for _ in range(max(repeats, 1)):
                t0 = time.perf_counter()
                res = run(states, args.steps, variant)
                jax.block_until_ready(jax.tree.leaves(res.state)[0])
                d = time.perf_counter() - t0
                if d < dt:
                    dt, best = d, variant

    flips = args.chains * (args.steps - 1)  # yields minus the initial record
    fps = flips / dt
    s = res.host_state()
    # the body that actually produced the winning time: 'lowered_bits' |
    # 'lowered' | 'bitboard' | 'board' | 'pallas' | 'general_dense' |
    # 'general' — scoreboards key on this, so a graph silently falling off
    # the fast path is visible
    if use_board:
        kernel_path = ("pallas" if args.pallas
                       else kboard.body_for(bg, spec, best))
    elif best is not None:
        kernel_path = best  # winner of the general_dense vs general race
    else:
        from flipcomplexityempirical_tpu.lower import dispatch as _dispatch
        kernel_path = _dispatch.kernel_path_for(g, spec)
    meta = {
        "device": ("cpu-fallback" if cpu_fallback else str(jax.devices()[0])),
        "path": ("pallas" if use_board and args.pallas
                 else "board" if use_board else "general"),
        "kernel_path": kernel_path,
        "graph": args.graph,
        "chains": args.chains,
        "steps": args.steps,
        "chunk": args.chunk,
        "grid": args.grid,
        "k": args.k,
        "seconds": round(dt, 3),
        "repeats": max(repeats, 1),
        "repeat_policy": "best",
        "mean_tries_per_step": float(np.asarray(s.tries_sum).mean()
                                     / (args.steps - 1)),
        "accept_rate": float(np.asarray(s.accept_count).mean()
                             / (args.steps - 1)),
    }
    if use_board and not args.pallas and (len(variants) > 1
                                          or args.body is not None):
        meta["body"] = (("lowered_bits" if best else "lowered") if lowered
                        else ("bitboard" if best else "int8"))

    if args.ess:
        # recorded pass at the winning variant: effective samples of the
        # cut trajectory per wall-clock second (independent chains add).
        # On the board AND general paths the history stays
        # DEVICE-resident and the Sokal-windowed ESS is computed on
        # device (stats.ess_device) — the timed region then measures
        # sampling + diagnostics, not a (C, T) x 4 history readback (on
        # a tunneled chip the readback alone was 18.8s vs 0.7s of chain,
        # round-5 records). The host f64 estimator cross-checks the
        # device value OUTSIDE the timed window ("ess_host_check":
        # relative difference).
        from flipcomplexityempirical_tpu.stats import ess as ess_fn
        from flipcomplexityempirical_tpu.stats import ess_device
        # both the board and general runners can keep the history
        # device-resident for on-device diagnostics; the pallas runner
        # still reads back, --ess-host opts out (HBM-bound horizons),
        # and CPU runs use the host f64 estimator so fallback records
        # stay comparable to the pre-device-diagnostics ones
        dev_hist = not (args.pallas or args.cpu or args.ess_host)
        # compile the collect=True kernel AND the ESS kernel outside the
        # timed window — at the TIMED history length (jit specializes on
        # T; warming at the warmup length would push the n_fft=2T FFT
        # compile inside the timed region)
        if dev_hist:
            warm = run(states, args.steps, best, record=True,
                       device_hist=True)
            jax.block_until_ready(ess_device(warm.history["cut_count"])[1])
        else:
            warm = run(states, args.warmup, best, record=True)
        jax.block_until_ready(jax.tree.leaves(warm.state)[0])
        # release the warm-up's full-length device history BEFORE the
        # timed run allocates its own — holding both doubles the
        # history's HBM watermark exactly at the headline measurement
        del warm
        t0 = time.perf_counter()
        if dev_hist:
            res_h = run(states, args.steps, best, record=True,
                        device_hist=True)
            ess_total = float(ess_device(res_h.history["cut_count"])[1])
        else:
            res_h = run(states, args.steps, best, record=True)
            jax.block_until_ready(jax.tree.leaves(res_h.state)[0])
            hist64 = np.asarray(res_h.history["cut_count"], np.float64)
            _, ess_total = ess_fn(hist64)
        d_rec = time.perf_counter() - t0
        # OUTSIDE the timed window (ESS/s stays comparable to earlier
        # records): the correctness-bar bottleneck ratio of the same
        # recorded trajectory, on the estimator matching the history's
        # residency (cut counts are integers, so both bin identically)
        if dev_hist:
            from flipcomplexityempirical_tpu.stats import (
                bottleneck_ratio_device, integer_thresholds)
            hist = res_h.history["cut_count"]
            phi, r_star = (float(v) for v in bottleneck_ratio_device(
                hist, integer_thresholds(hist)))
        else:
            from flipcomplexityempirical_tpu.stats import bottleneck_ratio
            # same integer level-set grid as the device path — the host
            # default would fall back to a 257-point linspace past 256
            # distinct values, making records non-comparable across
            # ess_on_device true/false
            phi, r_star = bottleneck_ratio(
                hist64, np.arange(hist64.min(), hist64.max() + 1.0))
        meta_ess = {
            "metric": "cut_ess_per_sec",
            "ess_total": round(float(ess_total), 1),
            "recorded_seconds": round(d_rec, 3),
            "value": round(float(ess_total) / d_rec, 2),
            "ess_on_device": dev_hist,
            # null (not NaN, which is invalid JSON) for a frozen observable
            "bottleneck_phi": (None if np.isnan(phi) else round(phi, 6)),
            "bottleneck_r": (None if np.isnan(r_star) else r_star),
        }
        if dev_hist:
            _, host_total = ess_fn(np.asarray(res_h.history["cut_count"],
                                              np.float64))
            meta_ess["ess_host_check"] = round(
                abs(float(host_total) - ess_total)
                / max(float(host_total), 1.0), 6)
        if args.record_every > 1:
            # ESS of the THINNED trajectory (thinning >~ the IAT trades
            # some measured ESS for a k-fold smaller history footprint)
            meta_ess["record_every"] = args.record_every
        print(json.dumps(meta_ess), file=sys.stderr)

    print(json.dumps(meta), file=sys.stderr)
    if args.graph != "square":
        metric = f"flips_per_sec_per_chip_{args.graph}"
    elif args.k == 2:
        metric = "flips_per_sec_per_chip_64x64"
    else:
        metric = f"flips_per_sec_per_chip_64x64_pair_k{args.k}"
    headline = {
        "metric": metric,
        "value": round(fps, 1),
        "unit": "flips/s",
        # a host-CPU stand-in cannot be compared to the per-chip TPU
        # target, so the ratio is null rather than a misreadable number
        # (ADVICE r4); the raw value + "chains" keep fallback records
        # comparable to EACH OTHER under the frozen fallback config
        "vs_baseline": (None if cpu_fallback else round(fps / 1.25e6, 4)),
        # interpretability tags (VERDICT r3): where the number ran, which
        # kernel body won, and the repeat policy behind it
        "device": meta["device"],
        "path": meta["path"],
        "kernel_path": meta["kernel_path"],
        "repeats": meta["repeats"],
        "repeat_policy": "best",
    }
    if args.graph != "square":
        headline["graph"] = args.graph
    if "body" in meta:
        headline["body"] = meta["body"]
    if cpu_fallback:
        # explicit stand-in: measured on host CPU because the accelerator
        # probe failed; vs_baseline still divides by the PER-CHIP target
        headline["cpu_fallback"] = True
    degradations = rdegrade.since(degrade_mark)
    if degradations:
        # the winning body was reached by falling off the intended
        # dispatch path — bench_compare refuses to gate such a record
        headline["degraded"] = True
        headline["degradations"] = degradations
    print(json.dumps(headline))

    if args.devstats and not args.pallas:
        # two recorded legs OUTSIDE the timed window: per-step readback
        # bytes of the history oracle path vs the device-resident
        # summary plane, from each leg's own event stream accounting
        import tempfile
        from flipcomplexityempirical_tpu.stats.accumulators import \
            DeviceAnalytics

        def _readback_leg(analytics):
            fd, jpath = tempfile.mkstemp(suffix=".jsonl")
            os.close(fd)
            try:
                with obs.Recorder(path=jpath) as lrec:
                    run(states, args.steps, best,
                        record=(analytics is None),
                        recorder=lrec, analytics=analytics)
                steps = rb = 0
                with open(jpath) as f:
                    for line in f:
                        e = json.loads(line)
                        if e.get("event") == "chunk":
                            steps += e.get("steps", 0)
                            rb += e.get("readback_bytes", 0)
                return rb, steps
            finally:
                os.unlink(jpath)

        rb_h, st_h = _readback_leg(None)
        rb_s, st_s = _readback_leg(
            DeviceAnalytics(args.chains, observable="cut_count"))
        per_h = rb_h / max(st_h, 1)
        per_s = rb_s / max(st_s, 1)
        devstats = {
            # higher is better (bench_compare gates on throughput-shaped
            # metrics): the factor by which the summary plane shrinks
            # the per-chunk device->host traffic
            "metric": "readback_summary_vs_history_ratio",
            "value": round(per_h / max(per_s, 1e-12), 2),
            "unit": "x",
            "readback_bytes_per_step": round(per_s, 3),
            "history_readback_bytes_per_step": round(per_h, 3),
            "path": meta["path"],
            "kernel_path": meta["kernel_path"],
            "chains": args.chains,
            "chunk": args.chunk,
            "device": meta["device"],
        }
        if cpu_fallback:
            devstats["cpu_fallback"] = True
        print(json.dumps(devstats))

    rec.close()


def _mesh_bench(args, cpu_fallback, g, plan, spec, rec):
    """The --mesh N flow: the 1/2/4/.../N scaling ladder through the
    sharded board train step (distribute.run_sharded), MULTICHIP record
    on stdout.

    Weak scaling: ``--chains`` chains PER CHIP at every rung, so the
    per-chip workload — and thus per-chip flips/s, the regression metric
    tools/bench_compare.py gates across differing device counts — stays
    constant up the ladder. Replica exchange is ON with a uniform beta
    ladder: every swap round runs the full all_gather + replicated
    selection over ICI (the scaling cost being measured) while the
    exchanged betas are identical, keeping the chain dynamics comparable
    to the single-chip headline. The timed passes run un-instrumented
    (NullRecorder); with --events a separate recorded pass at the full
    mesh follows the timing, on the per-host sink."""
    import jax
    import jax.numpy as jnp
    import flipcomplexityempirical_tpu as fce
    from flipcomplexityempirical_tpu import distribute
    from flipcomplexityempirical_tpu.kernel import board as kboard
    from flipcomplexityempirical_tpu.resilience import degrade as rdegrade

    degrade_mark = rdegrade.snapshot()
    if args.chains is None:
        # per-chip defaults: the single-chip peak on the real chip, the
        # frozen host sweet spot on CPU (module docstring)
        args.chains = 256 if args.cpu else (8192 if args.k == 2 else 4096)
    bits = None if args.body is None else (args.body == "bits")
    rounds = (args.steps - 1) // args.chunk
    warm_rounds = max((args.warmup - 1) // args.chunk, 1)
    repeats = max(args.repeats if args.repeats else 2, 1)

    ladder = [d for d in (1, 2, 4, 8, 16, 32, 64) if d < args.mesh]
    ladder.append(args.mesh)
    scaling = []
    body = None
    for n_dev in ladder:
        mesh = distribute.make_mesh(n_dev)
        chains = args.chains * n_dev
        bg, states, params = fce.sampling.init_board(
            g, plan, n_chains=chains, seed=0, spec=spec,
            base=args.base, pop_tol=args.pop_tol)
        states = distribute.shard_chain_batch(mesh, states)
        params = distribute.shard_chain_batch(mesh, params)
        step = distribute.make_board_train_step(
            bg, spec, mesh, inner_steps=args.chunk, exchange=True,
            bits=bits)
        body = step.kernel_path
        key = jax.random.PRNGKey(0)
        key, kw = jax.random.split(key)
        # compile + mix in; same inner_steps so the timed rounds reuse
        # the compiled step
        params, states, _ = distribute.run_sharded(
            step, params, states, rounds=warm_rounds,
            inner_steps=args.chunk, key=kw)
        states = states.replace(
            accept_count=jnp.zeros_like(states.accept_count),
            tries_sum=jnp.zeros_like(states.tries_sum),
            exhausted_count=jnp.zeros_like(states.exhausted_count))
        best = None
        for _ in range(repeats):
            key, kt = jax.random.split(key)
            params, states, info = distribute.run_sharded(
                step, params, states, rounds=rounds,
                inner_steps=args.chunk, key=kt)
            if best is None or info["wall_s"] < best["wall_s"]:
                best = info
        scaling.append({
            "devices": n_dev,
            "chains": chains,
            "seconds": round(best["wall_s"], 3),
            "flips_per_s": round(best["flips_per_s"], 1),
            "flips_per_s_per_chip": round(best["flips_per_s_per_chip"],
                                          1),
        })
        if n_dev == args.mesh and rec:
            # instrumented pass AFTER the timing: per-round chunk +
            # swap_round spans on the per-host event stream
            key, kr = jax.random.split(key)
            distribute.run_sharded(step, params, states, rounds=rounds,
                                   inner_steps=args.chunk, key=kr,
                                   recorder=rec)

    full = scaling[-1]
    dev0 = "cpu-fallback" if cpu_fallback else str(jax.devices()[0])
    meta = {
        "device": f"{dev0} x{args.mesh}",
        "devices": args.mesh,
        "path": "board",
        "kernel_path": body,
        "graph": args.graph,
        "chains": full["chains"],
        "chains_per_chip": args.chains,
        "steps": args.steps,
        "chunk": args.chunk,
        "grid": args.grid,
        "k": args.k,
        "seconds": full["seconds"],
        "repeats": repeats,
        "repeat_policy": "best",
        "scaling": scaling,
    }
    print(json.dumps(meta), file=sys.stderr)

    if args.graph != "square":
        metric = f"flips_per_sec_multichip_{args.graph}"
    else:
        metric = f"flips_per_sec_multichip_{args.grid}x{args.grid}"
        if args.k != 2:
            metric += f"_pair_k{args.k}"
    per_chip = full["flips_per_s_per_chip"]
    headline = {
        "metric": metric,
        "value": full["flips_per_s"],
        "unit": "flips/s",
        # per-chip throughput against the per-chip baseline target — the
        # ratio that stays meaningful when the device count changes
        # between rounds; null on the fallback stand-in as usual
        "vs_baseline": (None if cpu_fallback
                        else round(per_chip / 1.25e6, 4)),
        "device": meta["device"],
        "devices": args.mesh,
        "path": "board",
        "kernel_path": body,
        "body": body,
        "flips_per_s_per_chip": per_chip,
        "chains": full["chains"],
        "chains_per_chip": args.chains,
        "scaling": scaling,
        "scaling_efficiency": round(
            full["flips_per_s"]
            / (args.mesh * scaling[0]["flips_per_s"]), 4),
        "repeats": repeats,
        "repeat_policy": "best",
    }
    if args.graph != "square":
        headline["graph"] = args.graph
    if cpu_fallback:
        headline["cpu_fallback"] = True
    degradations = rdegrade.since(degrade_mark)
    if degradations:
        headline["degraded"] = True
        headline["degradations"] = degradations
    print(json.dumps(headline))


def _fleet_bench(args):
    """--fleet: the fleet admission/fairness record.

    Delegates to tools/loadtest.py's discrete-event simulator — the
    server's REAL TokenBucket + FairAdmission classes on a virtual
    clock, K simulated workers of constant service time. The scenario
    is FROZEN (500 tenants x 2 jobs, 16 workers, spread 4x total
    service time ≈ 25% utilization, seed 7): like the cpu_fallback
    kernel configuration, a fleet record is only interpretable against
    earlier fleet records if the shape never moves. The record's
    tenants+workers fields make bench_compare qualify it
    ``[tenants=500,workers=16]`` so it never gates a kernel metric."""
    from tools.loadtest import build_record, simulate

    tenants, jobs, workers, service_s, seed = 500, 2, 16, 1.0, 7
    spread = 4.0 * tenants * jobs * service_s / workers
    sim = simulate(tenants, jobs, workers, service_s, spread,
                   0.002, seed)
    record = build_record(
        sim["waits"], sim["turnarounds"], sim["rejected"], tenants,
        workers, jobs, "simulate",
        extra={"service_s": service_s, "spread_s": round(spread, 3),
               "admit_s": 0.002, "seed": seed,
               "makespan_s": round(sim["makespan_s"], 3)})
    print(json.dumps({"mode": "fleet", "frozen": True},
                     ), file=sys.stderr)
    print(json.dumps(record))


def _service_bench(args):
    """--service: the sweep-service tenant-efficiency record.

    Delegates to service.__main__.run_simulation — N coalescible
    tenants drained as ONE device batch vs a solo tenant, each cold for
    its own batch shape, so the ratio prices exactly what a tenant
    experiences: end-to-end turnaround including the XLA compile the
    service pays on their behalf. The record is a plain
    {"metric", "value"} dict, so tools/bench_compare.py gates it like
    any flips/s headline (higher is better; the service block in
    BASELINE.json sets the floor)."""
    import tempfile

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    from flipcomplexityempirical_tpu.obs import from_spec
    from flipcomplexityempirical_tpu.service.__main__ import run_simulation

    family = args.graph if args.graph in ("sec11", "frank") else "frank"
    chains = args.chains or 2
    outdir = tempfile.mkdtemp(prefix="bench_service_")
    with from_spec(args.events) as rec:
        record = run_simulation(tenants=args.tenants, chains=chains,
                                steps=args.steps, family=family,
                                outdir=outdir, recorder=rec)
    import jax
    meta = {
        "mode": "service",
        "outdir": outdir,
        "device": str(jax.devices()[0]),
        "n_devices": len(jax.devices()),
    }
    print(json.dumps(meta), file=sys.stderr)
    if record["device"] == "cpu":
        record["cpu_fallback"] = True
    print(json.dumps(record))


def _adaptive_bench(args):
    """--adaptive: the control-plane wall-clock-to-target-ESS record.

    One seeded sweep (two frank configs + one tempered ladder) is run
    twice in this process: ADAPTIVE — a control.ControlLoop with the
    EarlyStop and Ladder policies consulted at segment boundaries — and
    FIXED (the full schedule, no control). An untimed warmup pass runs
    the fixed schedule first so BOTH timed legs see a warm jit cache
    and identical prebuilt graphs; the timed region is the segment loop
    alone (rendering and graph build are identical per leg and
    excluded). Value = fixed_wall / adaptive_wall; > 1 means the loop
    reached the split-R-hat/ESS targets in strictly less wall clock
    than the fixed schedule spent. bench_compare qualifies the record
    per (family, policy)."""
    import time as _time

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    from flipcomplexityempirical_tpu.control import (ControlLoop,
                                                    EarlyStopPolicy,
                                                    LadderPolicy)
    from flipcomplexityempirical_tpu.experiments import driver as drv
    from flipcomplexityempirical_tpu.experiments.config import \
        ExperimentConfig
    from flipcomplexityempirical_tpu.obs import from_spec
    import jax

    steps = args.steps
    chains = args.chains or 4
    every = max(args.record_every,
                (steps // 6 // args.record_every) * args.record_every)
    shared = dict(pop_tol=0.1, total_steps=steps, n_chains=chains,
                  checkpoint_every=every,
                  record_every=args.record_every)
    configs = [
        ExperimentConfig(family="frank", alignment=2, base=1 / 0.3,
                         seed=3, **shared),
        ExperimentConfig(family="frank", alignment=1, base=1 / 0.3,
                         seed=16, **shared),
        ExperimentConfig(family="temper", alignment=0, base=1 / 0.3,
                         betas=(1.0, 0.9, 0.8, 0.7),
                         swap_every=max(every // 2, 10), seed=29,
                         **shared),
    ]
    loop = ControlLoop(policies=[
        EarlyStopPolicy(rhat_target=args.target_rhat,
                        ess_target=args.target_ess, patience=1),
        LadderPolicy(),
    ])
    with from_spec(args.events) as rec:
        loop.attach(recorder=rec)
        built = [(c,) + tuple(drv.build_graph_and_plan(c)[:2])
                 for c in configs]

        def _leg(control):
            t0 = _time.perf_counter()
            for c, g, plan in built:
                if c.family == "temper":
                    drv._run_temper(c, g, plan, None, recorder=rec,
                                    control=control)
                else:
                    drv._run_jax(c, g, plan, None, recorder=rec,
                                 control=control)
            return _time.perf_counter() - t0

        _leg(None)  # warmup: pays every compile, untimed
        adaptive_wall = _leg(loop)
        fixed_wall = _leg(None)

    device = jax.devices()[0]
    meta = {
        "mode": "adaptive",
        "device": str(device),
        "n_devices": len(jax.devices()),
        "configs": [c.tag for c in configs],
        "checkpoint_every": every,
    }
    print(json.dumps(meta), file=sys.stderr)
    record = {
        "metric": "wall_clock_to_target_ess",
        "value": round(fixed_wall / adaptive_wall, 4),
        "unit": "x",
        "family": "frank+temper",
        "policy": "early_stop+ladder",
        "adaptive_wall_s": round(adaptive_wall, 4),
        "fixed_wall_s": round(fixed_wall, 4),
        "targets": {"rhat": args.target_rhat, "ess": args.target_ess},
        "stops": [{"tag": a.tag, "step": a.step}
                  for a in loop.actions if a.kind == "stop"],
        "reshapes": [{"tag": a.tag, "step": a.step}
                     for a in loop.actions
                     if a.kind == "reshape_ladder"],
        "chains": chains,
        "steps": steps,
        "device": device.platform,
    }
    if device.platform == "cpu":
        record["cpu_fallback"] = True
    print(json.dumps(record))


def _workload_matrix_bench(args):
    """--workload-matrix: one throughput record per catalog workload.

    Each workload resolves through the registry's single materialisation
    path (the driver's own graph/spec builders) and runs its tuned shape
    twice — an untimed warmup pass that pays every compile, then the
    timed pass — so the record measures the steady-state segment loop.
    Records carry the workload name, chain family, variant, resolved
    dispatch rung, and both fingerprints; bench_compare names the metric
    ``workload_steps_per_s[workload=...]``, so the flip grid never gates
    against ReCom or a dual fixture. Stdout stays one JSON line
    (``{"mode": "workload-matrix", "results": [...]}``); per-run meta
    goes to stderr."""
    import time as _time

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    from flipcomplexityempirical_tpu import workloads
    from flipcomplexityempirical_tpu.experiments import driver as drv
    from flipcomplexityempirical_tpu.obs import from_spec

    names = (args.workloads.split(",") if args.workloads else
             ["sec11", "grid-k4", "dual-fixture", "recom-grid",
              "sec11-nobacktrack", "frank-lazy"])
    device = jax.devices()[0]
    results = []
    with from_spec(args.events) as rec:
        for name in names:
            r = workloads.resolve(name)   # graph + plan built untimed
            cfg = r.config

            def _leg():
                t0 = _time.perf_counter()
                drv._run_jax(cfg, r.graph, r.plan, None, recorder=rec)
                return _time.perf_counter() - t0

            _leg()          # warmup: pays the compile, untimed
            wall = _leg()
            work = cfg.total_steps * cfg.n_chains
            record = {
                "metric": "workload_steps_per_s",
                "value": round(work / wall, 2),
                "unit": "steps/s",
                "workload": name,
                "family": cfg.family,
                "chain": cfg.chain,
                "variant": cfg.variant,
                "kernel_path": r.kernel_path,
                "workload_fingerprint": r.workload.fingerprint(),
                "config_fingerprint": cfg.fingerprint(),
                "wall_s": round(wall, 4),
                "steps": cfg.total_steps,
                "chains": cfg.n_chains,
                "device": device.platform,
            }
            if device.platform == "cpu":
                record["cpu_fallback"] = True
            results.append(record)
            print(json.dumps({"workload": name, "wall_s": record["wall_s"],
                              "kernel_path": r.kernel_path}),
                  file=sys.stderr)

    meta = {
        "mode": "workload-matrix",
        "device": str(device),
        "n_devices": len(jax.devices()),
        "workloads": names,
    }
    print(json.dumps(meta), file=sys.stderr)
    print(json.dumps({"mode": "workload-matrix", "results": results}))


if __name__ == "__main__":
    main()
