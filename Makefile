# Convenience entry points; every target is a thin wrapper over a
# checked-in script so CI and humans run the same thing.

PYTHON ?= python

.PHONY: test obs-check mesh-check chaos-check bitpack-check \
	service-check preempt-check control-check workload-check \
	dense-check fleet-check obsfleet-check devstats-check lint

# tier-1 suite (the ROADMAP verify command without the log plumbing)
test:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q -m 'not slow'

# static telemetry gates: graftlint + event-stream schema/span check +
# Chrome-trace export validation over the committed fixture stream
obs-check:
	PYTHON=$(PYTHON) tools/ci_obs.sh

# multi-chip gates: per-host fixture streams merge through trace_export,
# plus a live 2-device forced-host bench --mesh smoke (fast-path body,
# per-chip flips/s, valid event stream)
mesh-check:
	PYTHON=$(PYTHON) tools/mesh_check.sh

# fault-tolerance gates: a seeded chaos sweep (injected checkpoint +
# segment faults) must recover byte-identically to a fault-free run,
# and a poison config must quarantine with a nonzero exit
chaos-check:
	PYTHON=$(PYTHON) JAX_PLATFORMS=cpu tools/chaos_check.sh

# bit-identity gate: the packed lowered_bits body vs the int8 lowered
# body on a small surgical grid must agree bit-for-bit (fast smoke; the
# full parity matrix is tests/test_bitboard_lowered.py)
bitpack-check:
	PYTHON=$(PYTHON) tools/bitpack_check.sh

# sweep-service gate: two coalescible tenants + one poison config must
# yield one coalesced batch (one compile_cache_miss), a quarantined
# poison job, and a valid merged event stream + namespaced heartbeats
service-check:
	PYTHON=$(PYTHON) tools/service_check.sh

# preemption gate: SIGTERM mid-batch must drain (exit 3), journal the
# requeues, and a recovered process must finish with per-tenant results
# byte-identical to uninterrupted runs — board AND general paths, plus
# a torn-journal-tail detection/repair leg
preempt-check:
	PYTHON=$(PYTHON) JAX_PLATFORMS=cpu tools/preempt_check.sh

# adaptive-control gate: G008 policy purity, a seeded CPU sweep where
# the control loop beats the fixed schedule to the split-R-hat/ESS
# targets (wall_clock_to_target_ess > 1.0x with journaled stops, valid
# stream, bench_compare-qualified record), and a SIGTERM drain whose
# recovery replays the journaled control_action sequence bit-identically
control-check:
	PYTHON=$(PYTHON) JAX_PLATFORMS=cpu tools/control_check.sh

# workload-catalog gate: every catalog entry resolves on its declared
# dispatch rung with stable distinct fingerprints, the dual-graph
# fixture and ReCom chain family run end to end through the real CLI
# with valid event streams, and the bench workload matrix emits
# [workload=...]-qualified records so families never cross-gate
workload-check:
	PYTHON=$(PYTHON) JAX_PLATFORMS=cpu tools/workload_check.sh

# general-dense gate (ISSUE 15): graftlint, chi2 exactness of the
# rejection-free general_dense body vs the enumerated stationary law,
# the >=2x CPU hex microbench over the legacy general kernel, and the
# general_dense -> general compile-fault degradation fall-through
dense-check:
	PYTHON=$(PYTHON) tools/dense_check.sh

# fleet gate (ISSUE 17): one HTTP front door + two worker processes +
# eight tenants + a worker.sigkill chaos fault — every job DONE with an
# artifact, the SIGKILLed worker's lease reclaimed by the survivor,
# fleet + run journals replay with zero corruption, no double
# execution, Jain fairness >= 0.8, schema-valid event streams
fleet-check:
	PYTHON=$(PYTHON) tools/fleet_check.sh

# fleet observability gate (ISSUE 18): 2-worker fleet smoke over the
# canonical $ROOT/events/ layout — mid-run /v1/metrics + /v1/fleet
# scrape, on-demand profile marker honored at a segment boundary and
# published as an artifact, per-worker heartbeat docs, the
# trace_export --fleet end-to-end trace-parenting gate, the SLO
# section with --strict tripping on an injected lease-expiry storm,
# and the <= 2% collector-overhead microbench gate
obsfleet-check:
	PYTHON=$(PYTHON) tools/obsfleet_check.sh

# device-resident analytics gate (ISSUE 20): G014 history-readback
# discipline in sampling/, the sec11 artifact set byte-identical
# between analytics='history' and 'summary', the NullRecorder /
# analytics hot path bit-identical, and the >= 100x board-path
# per-chunk readback reduction measured from honest readback_bytes
# event fields
devstats-check:
	PYTHON=$(PYTHON) tools/devstats_check.sh

# full pack: per-file rules G001-G010 + G014 plus the whole-program stage
# (G011 lock discipline, G012 durability protocol, G013 fault-site
# conformance — also scans the gate .sh scripts' --faults plans).
# Results are content-hash cached in .graftlint_cache.json.
lint:
	$(PYTHON) -m tools.graftlint flipcomplexityempirical_tpu tools
