# Convenience entry points; every target is a thin wrapper over a
# checked-in script so CI and humans run the same thing.

PYTHON ?= python

.PHONY: test obs-check lint

# tier-1 suite (the ROADMAP verify command without the log plumbing)
test:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q -m 'not slow'

# static telemetry gates: graftlint + event-stream schema/span check +
# Chrome-trace export validation over the committed fixture stream
obs-check:
	PYTHON=$(PYTHON) tools/ci_obs.sh

lint:
	$(PYTHON) -m tools.graftlint flipcomplexityempirical_tpu tools
